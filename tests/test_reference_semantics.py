"""Reference-semantics and joint-distribution tests (VERDICT round-1 item 8).

The framework's defaults deliberately correct the reference's math (quirks
Q1-Q4) and replace its combine rule; the knobs that *reproduce* reference
behavior must themselves be pinned:

* ``estimator="plain"`` - the reference combine rule Sigma_rc = rho Lam_r
  Lam_c' (+ Omega on the diagonal), ``divideconquer.m:186,:189``.
* ``x_prior_precision=g`` - the reference's g*I X-prior precision
  (``divideconquer.m:117``, quirk Q3).

Both are cross-checked against the independent NumPy twin.  Finally, a
Geweke joint-distribution test of the FULL jitted sweep (SURVEY.md section
4 names it): successive-conditional simulation (alternate Y | state with
the Gibbs sweep state | Y) must reproduce prior moments.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.models.conditionals import gibbs_sweep
from dcfm_tpu.models.priors import make_prior
from dcfm_tpu.models.state import SamplerState
from dcfm_tpu.ops.gamma import gamma_rate
from dcfm_tpu.reference_numpy import gibbs_numpy
from dcfm_tpu.utils.estimate import stitch_blocks
from dcfm_tpu.utils.preprocess import preprocess


def _rel_frob(A, B):
    return np.linalg.norm(A - B) / np.linalg.norm(B)


def test_plain_estimator_twin_parity():
    """estimator="plain" (the reference combine rule) agrees with the twin
    running the same rule - the claim "plain reproduces the reference" is a
    test, not a comment."""
    Y, _ = make_synthetic(120, 48, 3, seed=61)
    g, K, rho = 2, 3, 0.7
    pre = preprocess(Y, g, seed=0)
    blocks_np, _ = gibbs_numpy(
        pre.data.astype(np.float64), K, rho, 400, 400, seed=1,
        estimator="plain")
    cfg = FitConfig(
        model=ModelConfig(num_shards=g, factors_per_shard=K, rho=rho,
                          estimator="plain"),
        run=RunConfig(burnin=400, mcmc=400, thin=1, seed=0, num_chains=4))
    res = fit(Y, cfg)
    S_np = stitch_blocks(blocks_np)
    S_jx = stitch_blocks(res.sigma_blocks.astype(np.float64))
    # Looser than the scaled-estimator parity test (0.05): the plain rule is
    # NOT invariant to the slow-mixing Lambda<->eta scale ridge, so a single
    # chain's Monte Carlo average sits wherever its ridge walk happened to
    # wander.  De-flaked by cross-chain pooling: sigma_blocks is the
    # equal-weight average over num_chains=4 independent chains, so the
    # pooled estimate averages four independent ridge points instead of
    # betting the test on one.  Measured at this schedule (400+400, seed
    # 0): pooled-vs-twin 0.073 (single chain: 0.123) - the 0.15 bound has
    # 2x headroom over the pooled measurement where the old single-chain
    # 0.20 had 1.6x, and the pooled statistic is stabler by construction.
    # (Exactness of the plain rule itself is pinned separately:
    # tests/test_draws.py rebuilds the accumulated plain Sigma from the
    # stored draws with the reference formula to 2e-4.)
    assert _rel_frob(S_jx, S_np) < 0.15


def test_plain_vs_scaled_differ_offdiagonal():
    """Sanity: the two estimators are genuinely different rules (the plain
    rule pins cross-blocks to rho * Lam_r Lam_c')."""
    Y, _ = make_synthetic(100, 32, 2, seed=63)
    base = dict(num_shards=2, factors_per_shard=2, rho=0.6)
    run = RunConfig(burnin=150, mcmc=150, thin=1, seed=0)
    S_plain = fit(Y, FitConfig(
        model=ModelConfig(estimator="plain", **base), run=run)).sigma_blocks
    S_scaled = fit(Y, FitConfig(
        model=ModelConfig(estimator="scaled", **base), run=run)).sigma_blocks
    off_diff = np.abs(S_plain[0, 1] - S_scaled[0, 1]).max()
    assert off_diff > 1e-4


def test_x_prior_precision_reproduces_reference_q3():
    """x_prior_precision=g (the reference's g*I prior term,
    ``divideconquer.m:117``) cross-checked against the twin with the same
    setting; and it measurably changes the X conditional vs the default."""
    Y, _ = make_synthetic(100, 32, 2, seed=67)
    g, K, rho = 2, 2, 0.8
    pre = preprocess(Y, g, seed=0)
    blocks_np, _ = gibbs_numpy(
        pre.data.astype(np.float64), K, rho, 300, 300, seed=1,
        x_prior_precision=float(g))
    cfg = FitConfig(
        model=ModelConfig(num_shards=g, factors_per_shard=K, rho=rho,
                          x_prior_precision=float(g)),
        run=RunConfig(burnin=300, mcmc=300, thin=1, seed=0))
    res = fit(Y, cfg)
    assert _rel_frob(
        stitch_blocks(res.sigma_blocks.astype(np.float64)),
        stitch_blocks(blocks_np)) < 0.06
    # the knob does something: with rho high and small n, X's posterior
    # shrinks visibly harder under the g*I prior
    res_default = fit(Y, FitConfig(
        model=ModelConfig(num_shards=g, factors_per_shard=K, rho=rho),
        run=RunConfig(burnin=300, mcmc=300, thin=1, seed=0)))
    x_g = float(np.mean(np.asarray(res.state.X) ** 2))
    x_1 = float(np.mean(np.asarray(res_default.state.X) ** 2))
    assert x_g != pytest.approx(x_1, rel=1e-3)


# ---------------------------------------------------------------------------
# Geweke joint-distribution test of the full sweep
# ---------------------------------------------------------------------------

# Tiny model; hyperparameters chosen so every monitored moment is finite
# (as=4 keeps E[1/ps] and Var[1/ps] finite; the statistics below are
# log-scale or second-moment, all finite under every prior - in particular
# mean(Lambda^2) / mean(Y^2) are replaced by their log-scale versions for
# the horseshoe, whose half-Cauchy local scales have no finite mean).
_G, _N, _P, _K, _RHO = 2, 6, 4, 2, 0.7
_AS, _BS = 4.0, 2.0


def _geweke_cfg(prior_name="mgp"):
    return ModelConfig(num_shards=_G, factors_per_shard=_K, rho=_RHO,
                       prior=prior_name, as_=_AS, bs=_BS)


def _prior_shrinkage_draw(key, prior):
    """One shard's prior-state pytree drawn from the PRIOR (not the chain
    init): mgp/dl's ``init`` already draws from the prior; the horseshoe's
    ``init`` is the deterministic all-ones chain start, so its hierarchy
    (Makalic-Schmidt: nu, xi ~ iG(1/2, 1); lam2 | nu ~ iG(1/2, 1/nu);
    tau2 | xi ~ iG(1/2, 1/xi)) is sampled here."""
    if prior.name == "horseshoe":
        from dcfm_tpu.ops.gamma import inverse_gamma_rate
        k1, k2, k3, k4 = jax.random.split(key, 4)
        nu = inverse_gamma_rate(k1, 0.5, jnp.ones((_P, _K)))
        lam2 = inverse_gamma_rate(k2, 0.5, 1.0 / nu)
        xi = inverse_gamma_rate(k3, 0.5, jnp.ones(()))
        tau2 = inverse_gamma_rate(k4, 0.5, 1.0 / xi)
        return {"lam2": lam2, "nu": nu, "tau2": tau2, "xi": xi}
    return prior.init(key, _P, _K)


def _prior_state(key, prior):
    """Draw a full SamplerState from the prior (matches state.init_state's
    distributions, but with Lambda ~ N(0, 1/row_precision) instead of
    zeros - the Geweke test needs the exact prior, not the reference's
    zero init)."""
    k_x, k_shard = jax.random.split(key)
    X = jax.random.normal(k_x, (_N, _K))

    def init_one(g):
        kg = jax.random.fold_in(k_shard, g)
        k_ps, k_z, k_prior, k_lam = jax.random.split(kg, 4)
        ps = gamma_rate(k_ps, _AS, _BS, sample_shape=(_P,))
        Z = jax.random.normal(k_z, (_N, _K))
        prior_state = _prior_shrinkage_draw(k_prior, prior)
        plam = prior.row_precision(prior_state)
        Lam = jax.random.normal(k_lam, (_P, _K)) / jnp.sqrt(plam)
        return Lam, Z, ps, prior_state

    Lam, Z, ps, prior_state = jax.vmap(init_one)(jnp.arange(_G))
    return SamplerState(Lambda=Lam, Z=Z, X=X, ps=ps, prior=prior_state)


def _sample_Y(key, state):
    """Y | state: Y_m = eta_m Lam_m' + N(0, diag(1/ps_m))."""
    eta = (jnp.sqrt(_RHO) * state.X[None]
           + jnp.sqrt(1.0 - _RHO) * state.Z)
    mean = jnp.einsum("gnk,gpk->gnp", eta, state.Lambda)
    noise = jax.random.normal(key, mean.shape) / jnp.sqrt(
        state.ps[:, None, :])
    return mean + noise


def _stats_fn(prior_name):
    """Per-prior scalar functionals with finite prior variance, covering
    every Gibbs site (shared sites + each prior's own hierarchy)."""
    def shared(state, Y):
        return [jnp.mean(jnp.log(state.ps)),
                jnp.mean(state.Z ** 2),
                jnp.mean(state.X ** 2)]

    if prior_name == "mgp":
        def stats(state, Y):
            return jnp.stack(shared(state, Y) + [
                jnp.mean(jnp.log(state.prior["psijh"])),
                jnp.mean(jnp.log(state.prior["delta"])),
                jnp.mean(state.Lambda ** 2),
                jnp.mean(Y ** 2)])
        return stats, ("log_ps", "Z2", "X2", "log_psi", "log_delta",
                       "lam2", "Y2")
    if prior_name == "horseshoe":
        # half-Cauchy scales: no finite mean for lam2/tau2 or anything
        # downstream (Lambda^2, Y^2) - monitor on the log scale throughout
        def stats(state, Y):
            return jnp.stack(shared(state, Y) + [
                jnp.mean(jnp.log(state.prior["lam2"])),
                jnp.mean(jnp.log(state.prior["nu"])),
                jnp.mean(jnp.log(state.prior["tau2"])),
                jnp.mean(jnp.log(state.prior["xi"])),
                jnp.mean(jnp.log(state.Lambda ** 2)),
                jnp.mean(jnp.log(Y ** 2))])
        return stats, ("log_ps", "Z2", "X2", "log_lam2", "log_nu",
                       "log_tau2", "log_xi", "log_LamSq", "log_Y2")
    def stats(state, Y):  # dl
        return jnp.stack(shared(state, Y) + [
            jnp.mean(jnp.log(state.prior["psi"])),
            jnp.mean(jnp.log(state.prior["phi"])),
            jnp.mean(jnp.log(state.prior["tau"])),
            jnp.mean(state.Lambda ** 2),
            jnp.mean(Y ** 2)])
    return stats, ("log_ps", "Z2", "X2", "log_psi", "log_phi", "log_tau",
                   "lam2", "Y2")


@pytest.mark.slow
@pytest.mark.parametrize("prior_name", ["mgp", "horseshoe", "dl"])
def test_geweke_joint_distribution(prior_name):
    """Marginal-conditional (prior) vs successive-conditional (prior
    transported through the full Gibbs sweep) moments must agree.  A bug in
    ANY conditional - wrong weighting, wrong Cholesky orientation, wrong
    shape/rate, cross-shard leakage - shifts the stationary distribution of
    the successive chain away from the prior and fails the z-test.
    Parametrized over all three shrinkage priors so the horseshoe/DL
    hierarchies' cross-conditional wiring is validated by the same joint
    test as MGP, not only by per-conditional moment checks."""
    cfg = _geweke_cfg(prior_name)
    prior = make_prior(cfg)
    stats, stat_names = _stats_fn(prior_name)
    M_MARG = 6000
    # Many SHORT independent successive chains instead of one long one: a
    # successive-conditional chain started from an exact prior draw is
    # stationary from step 0, so the final states of R independent chains
    # are R i.i.d. draws from the kernel's stationary distribution - clean
    # sqrt(R) standard errors.  A single long chain cannot test the
    # horseshoe: its global scale's autocorrelation time exceeds 10^4
    # sweeps (measured: batch-means SE still growing at batch 400), so no
    # feasible length yields an honest SE.  A biased kernel still fails
    # here because its T-step distribution drifts away from the prior.
    R_CHAINS = 3000
    T_STEPS = 40

    # marginal-conditional: independent prior draws
    def marg_one(key):
        k1, k2 = jax.random.split(key)
        state = _prior_state(k1, prior)
        Y = _sample_Y(k2, state)
        return stats(state, Y)

    marg = np.asarray(jax.jit(jax.vmap(marg_one))(
        jax.random.split(jax.random.key(0), M_MARG)))

    # successive-conditional: Y | state then state | Y via the real sweep,
    # T steps from a prior draw; report the final (state, Y) functionals
    def succ_one(key):
        k0, kY, k_steps = jax.random.split(key, 3)

        def body(state, k):
            ky, ks = jax.random.split(k)
            Y = _sample_Y(ky, state)
            return gibbs_sweep(ks, Y, state, cfg, prior)[0], None

        state, _ = jax.lax.scan(body, _prior_state(k0, prior),
                                jax.random.split(k_steps, T_STEPS))
        return stats(state, _sample_Y(kY, state))

    succ = np.asarray(jax.jit(jax.vmap(succ_one))(
        jax.random.split(jax.random.key(1), R_CHAINS)))

    for i, name in enumerate(stat_names):
        m1, m2 = marg[:, i].mean(), succ[:, i].mean()
        se1 = marg[:, i].std(ddof=1) / np.sqrt(marg.shape[0])
        se2 = succ[:, i].std(ddof=1) / np.sqrt(succ.shape[0])
        z = abs(m1 - m2) / np.sqrt(se1 ** 2 + se2 ** 2)
        assert z < 5.0, \
            f"Geweke[{prior_name}] z[{name}] = {z:.2f} ({m1:.4f} vs {m2:.4f})"
