"""Chaos lane: scheduled faults, supervised resume, divergence rewind.

Every failure mode the resilience subsystem claims to survive is
exercised ON PURPOSE here, via the deterministic fault harness
(``DCFM_FAULT_PLAN``, resilience/faults.py):

* kill-at-iteration under ``dcfm-tpu fit --supervise`` resumes to a
  Sigma BIT-IDENTICAL to the uninterrupted run (the acceptance demo);
* a pre-save kill pins the checkpoint below the trigger, so every
  relaunch dies at the same iteration - the supervisor must abort with
  the typed PoisonedRunError instead of crash-looping;
* torn writes and bit-flips produce the typed CheckpointCorruptError
  and the retained-generation fallback;
* an injected divergence (poison_state) trips the sentinel, which
  rewinds to the last checkpoint and finishes with a finite posterior.

The subprocess tests run the REAL CLI (real SIGKILL, real resume), so
this file also rides the crash-isolated lane in scripts/ci_check.sh.
"""

import json
import os
import subprocess
import sys
import time
from typing import NamedTuple

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.resilience import faults
from dcfm_tpu.resilience.faults import FaultPlan, FaultPlanError
from dcfm_tpu.resilience.sentinel import ChainDivergedError
from dcfm_tpu.utils.checkpoint import (
    CheckpointCorruptError, load_checkpoint, read_checkpoint_meta,
    save_checkpoint, verify_checkpoint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    """No fault plan leaks across tests (the harness is process-global)."""
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def data():
    Y, _ = make_synthetic(n=40, p=24, k_true=3, seed=7)
    return Y


def _cfg(**kw):
    return FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.8),
        run=RunConfig(burnin=16, mcmc=16, thin=2, seed=3, chunk_size=8),
        **kw)


class _CarryLike(NamedTuple):
    a: np.ndarray
    b: np.ndarray
    iteration: np.ndarray


def _carry():
    return _CarryLike(a=np.arange(64.0), b=np.ones((32, 32)),
                      iteration=np.int32(4))


def _child_env(plan=None):
    """Environment for CLI children: CPU platform + the shared XLA
    compile cache (the suite's wall-clock is compile-dominated)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    env.pop("DCFM_FAULT_PLAN", None)
    if plan is not None:
        env["DCFM_FAULT_PLAN"] = json.dumps(plan)
    return env


def _cli_fit(data_path, out, extra, env):
    return subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "fit", data_path,
         "--shards", "2", "--factors", "6", "--burnin", "16",
         "--mcmc", "16", "--thin", "2", "--chunk-size", "8",
         "--out", out] + extra,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)


# ---------------------------------------------------------------------------
# fault-plan harness units
# ---------------------------------------------------------------------------

def test_fault_plan_validation():
    with pytest.raises(FaultPlanError, match="'faults' list"):
        FaultPlan({"nope": []})
    with pytest.raises(FaultPlanError, match="unknown op"):
        FaultPlan({"faults": [{"op": "explode"}]})
    with pytest.raises(FaultPlanError, match="at_iteration"):
        FaultPlan({"faults": [{"op": "kill"}]})
    with pytest.raises(FaultPlanError, match="at_write"):
        FaultPlan({"faults": [{"op": "bit_flip"}]})
    assert FaultPlan({"faults": []}).faults == []


def test_fault_plan_from_env_and_file(tmp_path, monkeypatch):
    faults.clear()
    monkeypatch.setenv(faults.ENV_VAR, '{"faults": []}')
    assert faults.fault_plan() is not None
    faults.clear()
    p = tmp_path / "plan.json"
    p.write_text('{"faults": [{"op": "kill", "at_iteration": 4}]}')
    monkeypatch.setenv(faults.ENV_VAR, f"@{p}")
    assert len(faults.fault_plan().faults) == 1
    faults.clear()
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.fault_plan() is None


def test_kill_fires_only_for_runs_that_started_below_trigger():
    plan = FaultPlan({"faults": [{"op": "kill", "at_iteration": 16}]})
    # a resumed run already past the trigger must not re-die: no fault
    # matches, so maybe_kill is a no-op (the process survives this call)
    plan.maybe_kill(24, 16, "post_save")
    # the boundary below the trigger doesn't fire either
    assert plan._boundary_due("kill", "post_save", 8, 0) is None
    # crossing fires exactly once
    assert plan._boundary_due("kill", "post_save", 16, 0) is not None
    assert plan._boundary_due("kill", "post_save", 24, 0) is None


def test_io_error_and_delay_faults(tmp_path, data):
    """io_error surfaces as OSError from the save; io_delay stalls it."""
    ck = str(tmp_path / "io.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "io_error", "target": "checkpoint", "at_write": 1}]})
    with pytest.raises(OSError, match="injected"):
        save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install({"faults": [
        {"op": "io_delay", "target": "checkpoint", "seconds": 0.2,
         "at_write": 1}]})
    t0 = time.perf_counter()
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    assert time.perf_counter() - t0 >= 0.2
    # write #2 has no fault: fast and intact
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    assert verify_checkpoint(ck)["crc_verified"]


def test_torn_write_fault_detected(tmp_path):
    """A torn write (file truncated after the atomic rename) leaves a
    file the loaders refuse - never a silent partial resume."""
    ck = str(tmp_path / "torn.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "torn_write", "target": "checkpoint", "at_write": 1,
         "keep_fraction": 0.5}]})
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install(None)
    with pytest.raises(Exception):       # truncated zip container
        read_checkpoint_meta(ck)
    with pytest.raises(Exception):
        verify_checkpoint(ck)


def test_bit_flip_fault_caught_by_crc(tmp_path):
    """bit_flip corrupts AFTER the CRCs are computed - exactly the silent
    corruption the integrity format exists to catch, surfaced as the
    typed CheckpointCorruptError by both verify and load."""
    ck = str(tmp_path / "flip.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "bit_flip", "target": "checkpoint", "at_write": 1,
         "leaf": "leaf_0"}]})
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install(None)
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        verify_checkpoint(ck)
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        load_checkpoint(ck, carry)


# ---------------------------------------------------------------------------
# divergence sentinel
# ---------------------------------------------------------------------------

def test_sentinel_rewind_recovers_finite_posterior(tmp_path, data):
    """An injected mid-run divergence (poison_state) trips the sentinel:
    the chain rewinds to the last checkpoint with a re-lineaged key and
    escalated jitter, and the fit completes with a finite posterior and
    a zero non-finite health count (the garbage chunks were discarded,
    not accumulated).  Documented NON-bit-exact vs an undiverged run."""
    ck = str(tmp_path / "sent.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1)
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    res = fit(data, cfg)
    assert res.sentinel_rewinds == 1
    assert np.isfinite(res.Sigma).all()
    assert float(np.asarray(res.stats.nonfinite_count)) == 0.0
    assert float(np.asarray(res.stats.acc_nonfinite)) == 0.0


def test_sentinel_abort_without_checkpoint(data):
    """No checkpoint -> nothing to rewind to: the sentinel aborts with
    the typed error at the boundary where divergence was detected,
    instead of completing with garbage."""
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    with pytest.raises(ChainDivergedError) as ei:
        fit(data, _cfg())
    assert ei.value.iteration == 24          # poisoned 16, detected at 24
    assert ei.value.rewinds == 0


def test_sentinel_off_preserves_old_behavior(data):
    """sentinel='off': the divergence runs to completion and poisons the
    result (the pre-sentinel behavior, kept reachable on purpose - it is
    what the sentinel's default protects against)."""
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    res = fit(data, _cfg(sentinel="off"))
    assert float(np.asarray(res.stats.nonfinite_count)) > 0


def test_sentinel_rewind_budget_exhaustion(tmp_path, data):
    """Every retry re-diverging must exhaust the budget and raise - not
    loop forever.  poison_state faults at every post-rewind boundary."""
    ck = str(tmp_path / "budget.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1,
               sentinel_max_rewinds=1)
    faults.install({"faults": [
        {"op": "poison_state", "at_iteration": 16},
        {"op": "poison_state", "at_iteration": 16}]})
    with pytest.raises(ChainDivergedError, match="budget"):
        fit(data, cfg)


def test_healthy_chain_bitwise_unaffected_by_sentinel(tmp_path, data):
    """The sentinel only READS the per-chunk stats: a healthy chain's
    result is bit-identical with the sentinel on (default) and off."""
    res_on = fit(data, _cfg())
    res_off = fit(data, _cfg(sentinel="off"))
    np.testing.assert_array_equal(res_on.sigma_blocks, res_off.sigma_blocks)
    assert res_on.sentinel_rewinds == 0


# ---------------------------------------------------------------------------
# supervised runs (real CLI children, real SIGKILL)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def data_file(tmp_path_factory, data):
    d = tmp_path_factory.mktemp("chaos")
    p = str(d / "Y.npy")
    np.save(p, data)
    return p


def test_supervised_kill_resume_bit_exact(tmp_path, data_file):
    """THE acceptance demo: DCFM_FAULT_PLAN SIGKILLs the child at a
    mid-run iteration under `dcfm-tpu fit --supervise`; the supervisor
    resumes it and the final Sigma is BIT-IDENTICAL to the uninterrupted
    run's."""
    ref = str(tmp_path / "ref.npy")
    proc = _cli_fit(data_file, ref, [], _child_env())
    assert proc.returncode == 0, proc.stderr

    out = str(tmp_path / "sup.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [{"op": "kill", "at_iteration": 16,
                        "when": "post_save"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1", "--keep-last", "2",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stderr.strip().splitlines()[-1])
    assert report["launches"] == 2           # died once, resumed once
    assert report["deaths"][0][0] == -9      # a real SIGKILL
    assert report["final_iteration"] == 32
    np.testing.assert_array_equal(np.load(ref), np.load(out))


def test_supervised_poison_iteration_aborts(tmp_path, data_file):
    """A pre-save kill pins the checkpoint below the trigger: every
    relaunch dies at the same iteration.  The supervisor must abort with
    the typed PoisonedRunError after the second same-iteration death -
    exactly 2 launches, never a crash-loop."""
    out = str(tmp_path / "p.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [{"op": "kill", "at_iteration": 16,
                        "when": "pre_save"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 3, proc.stderr
    err = json.loads(proc.stderr.strip().splitlines()[-1])
    assert err["error"] == "PoisonedRunError"
    assert err["iteration"] == 8             # the save before the kill point
    assert err["checkpoint"] == ck
    assert proc.stderr.count("launch #") == 2


def test_supervised_corrupt_checkpoint_falls_back(tmp_path, data_file):
    """Acceptance criterion: a corrupted latest checkpoint is detected by
    CRC and the supervisor resumes from the previous retained one.  The
    plan bit-flips the save at iteration 16 and kills the child there;
    the supervisor demotes the corrupt file, promotes .bak1 (iteration
    8), and the run still completes bit-identically."""
    ref = str(tmp_path / "ref.npy")
    proc = _cli_fit(data_file, ref, [], _child_env())
    assert proc.returncode == 0, proc.stderr

    out = str(tmp_path / "c.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [
        {"op": "kill", "at_iteration": 16, "when": "post_save"},
        {"op": "bit_flip", "target": "checkpoint", "at_write": 2,
         "path_re": "ck.npz$"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1", "--keep-last", "2",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stderr.strip().splitlines()[-1])
    # TWO fallbacks: the mid-run one (kill at the flipped write) and the
    # exit pass - this plan's per-process write counter flips child 3's
    # write #2, i.e. the FINAL save, and the supervisor must leave the
    # live slot verified (newest clean generation promoted) on the way
    # out so a future resume doesn't trip over bad bytes
    assert report["corrupt_fallbacks"] == 2
    assert report["final_iteration"] == 24      # newest CLEAN generation
    assert "promoted retained checkpoint" in proc.stderr
    np.testing.assert_array_equal(np.load(ref), np.load(out))


@pytest.mark.slow
def test_supervise_api_returns_full_fitresult(tmp_path, data):
    """The API entry point: supervise(Y, cfg) runs the chain in children
    through an injected SIGKILL and returns a real FitResult whose Sigma
    is bit-identical to an in-process uninterrupted fit."""
    from dcfm_tpu.resilience import supervise

    res_ref = fit(data, _cfg())
    ck = str(tmp_path / "api.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1)
    env_plan = json.dumps(
        {"faults": [{"op": "kill", "at_iteration": 16,
                     "when": "post_save"}]})
    old = os.environ.get(faults.ENV_VAR)
    os.environ[faults.ENV_VAR] = env_plan
    try:
        # the PARENT must not execute the plan (it would SIGKILL the test
        # process at its no-op resume): neutralize it in-process while
        # the children inherit it from the environment
        faults.install({"faults": []})
        res = supervise(data, cfg, backoff_base=0.05)
    finally:
        if old is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = old
    np.testing.assert_array_equal(res.sigma_blocks, res_ref.sigma_blocks)
    np.testing.assert_array_equal(res.Sigma, res_ref.Sigma)


def test_supervise_requires_checkpoint(data):
    from dcfm_tpu.resilience import supervise

    with pytest.raises(ValueError, match="checkpoint_path"):
        supervise(data, _cfg())
    with pytest.raises(ValueError, match="full"):
        supervise(data, _cfg(checkpoint_path="x", checkpoint_mode="light"))


def test_supervise_report_attached_to_fitresult(tmp_path, data):
    """API callers see the supervision telemetry, not just the CLI's
    stderr JSON: a crash-free supervise() attaches a report with one
    launch and no deaths."""
    from dcfm_tpu.resilience import supervise

    ck = str(tmp_path / "rep.npz")
    res = supervise(data, _cfg(checkpoint_path=ck), backoff_base=0.05)
    rep = res.supervise_report
    assert rep is not None and rep.launches == 1 and rep.deaths == []
    assert rep.final_iteration == 32
    # a plain fit has none
    assert fit(data, _cfg()).supervise_report is None
