"""Chaos lane: scheduled faults, supervised resume, divergence rewind.

Every failure mode the resilience subsystem claims to survive is
exercised ON PURPOSE here, via the deterministic fault harness
(``DCFM_FAULT_PLAN``, resilience/faults.py):

* kill-at-iteration under ``dcfm-tpu fit --supervise`` resumes to a
  Sigma BIT-IDENTICAL to the uninterrupted run (the acceptance demo);
* a pre-save kill pins the checkpoint below the trigger, so every
  relaunch dies at the same iteration - the supervisor must abort with
  the typed PoisonedRunError instead of crash-looping;
* torn writes and bit-flips produce the typed CheckpointCorruptError
  and the retained-generation fallback;
* an injected divergence (poison_state) trips the sentinel, which
  rewinds to the last checkpoint and finishes with a finite posterior.

The subprocess tests run the REAL CLI (real SIGKILL, real resume), so
this file also rides the crash-isolated lane in scripts/ci_check.sh.
"""

import json
import os
import subprocess
import sys
import time
from typing import NamedTuple

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.resilience import faults
from dcfm_tpu.resilience.faults import FaultPlan, FaultPlanError
from dcfm_tpu.resilience.sentinel import ChainDivergedError
from dcfm_tpu.utils.checkpoint import (
    CheckpointCorruptError, load_checkpoint, read_checkpoint_meta,
    save_checkpoint, verify_checkpoint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    """No fault plan leaks across tests (the harness is process-global)."""
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def data():
    Y, _ = make_synthetic(n=40, p=24, k_true=3, seed=7)
    return Y


def _cfg(**kw):
    return FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.8),
        run=RunConfig(burnin=16, mcmc=16, thin=2, seed=3, chunk_size=8),
        **kw)


class _CarryLike(NamedTuple):
    a: np.ndarray
    b: np.ndarray
    iteration: np.ndarray


def _carry():
    return _CarryLike(a=np.arange(64.0), b=np.ones((32, 32)),
                      iteration=np.int32(4))


def _child_env(plan=None):
    """Environment for CLI children: CPU platform + the shared XLA
    compile cache (the suite's wall-clock is compile-dominated)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    env.pop("DCFM_FAULT_PLAN", None)
    if plan is not None:
        env["DCFM_FAULT_PLAN"] = json.dumps(plan)
    return env


def _cli_fit(data_path, out, extra, env):
    return subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "fit", data_path,
         "--shards", "2", "--factors", "6", "--burnin", "16",
         "--mcmc", "16", "--thin", "2", "--chunk-size", "8",
         "--out", out] + extra,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)


# ---------------------------------------------------------------------------
# fault-plan harness units
# ---------------------------------------------------------------------------

def test_fault_plan_validation():
    with pytest.raises(FaultPlanError, match="'faults' list"):
        FaultPlan({"nope": []})
    with pytest.raises(FaultPlanError, match="unknown op"):
        FaultPlan({"faults": [{"op": "explode"}]})
    with pytest.raises(FaultPlanError, match="at_iteration"):
        FaultPlan({"faults": [{"op": "kill"}]})
    with pytest.raises(FaultPlanError, match="at_write"):
        FaultPlan({"faults": [{"op": "bit_flip"}]})
    assert FaultPlan({"faults": []}).faults == []


def test_fault_plan_from_env_and_file(tmp_path, monkeypatch):
    faults.clear()
    monkeypatch.setenv(faults.ENV_VAR, '{"faults": []}')
    assert faults.fault_plan() is not None
    faults.clear()
    p = tmp_path / "plan.json"
    p.write_text('{"faults": [{"op": "kill", "at_iteration": 4}]}')
    monkeypatch.setenv(faults.ENV_VAR, f"@{p}")
    assert len(faults.fault_plan().faults) == 1
    faults.clear()
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.fault_plan() is None


def test_kill_fires_only_for_runs_that_started_below_trigger():
    plan = FaultPlan({"faults": [{"op": "kill", "at_iteration": 16}]})
    # a resumed run already past the trigger must not re-die: no fault
    # matches, so maybe_kill is a no-op (the process survives this call)
    plan.maybe_kill(24, 16, "post_save")
    # the boundary below the trigger doesn't fire either
    assert plan._boundary_due("kill", "post_save", 8, 0) is None
    # crossing fires exactly once
    assert plan._boundary_due("kill", "post_save", 16, 0) is not None
    assert plan._boundary_due("kill", "post_save", 24, 0) is None


def test_kill_event_validation_and_gates(monkeypatch):
    """kill_event needs an event name; the process and launch gates keep
    a shared pod plan from firing in the wrong process or launch (the
    calls below would SIGKILL the test process if the gates leaked)."""
    with pytest.raises(FaultPlanError, match="event"):
        FaultPlan({"faults": [{"op": "kill_event"}]})
    plan = FaultPlan({"faults": [
        {"op": "kill_event", "event": "sidecar_gate", "process": 1,
         "at_launch": 2}]})
    # no DCFM_FAULT_PROCESS at all: a process-gated fault never fires
    monkeypatch.delenv(faults.PROCESS_ENV_VAR, raising=False)
    monkeypatch.setenv(faults.LAUNCH_ENV_VAR, "2")
    plan.maybe_kill_event("sidecar_gate")
    # right process, wrong launch
    monkeypatch.setenv(faults.PROCESS_ENV_VAR, "1")
    monkeypatch.setenv(faults.LAUNCH_ENV_VAR, "1")
    plan.maybe_kill_event("sidecar_gate")
    # right process and launch but a different event / occurrence
    monkeypatch.setenv(faults.LAUNCH_ENV_VAR, "2")
    plan.maybe_kill_event("resume_gate")
    # boundary kills honor the same gates
    bplan = FaultPlan({"faults": [
        {"op": "kill", "at_iteration": 8, "process": 0}]})
    assert bplan._boundary_due("kill", "post_save", 8, 0) is None
    monkeypatch.setenv(faults.PROCESS_ENV_VAR, "0")
    assert bplan._boundary_due("kill", "post_save", 8, 0) is not None


def test_write_faults_honor_launch_gate(tmp_path, monkeypatch):
    """An at_launch-gated io_error fires in launch 1 and is silent in
    launch 2 - the shape the fuzz scheduler leans on so relaunches can
    finish clean."""
    ck = str(tmp_path / "gate.npz")
    carry = _carry()
    monkeypatch.setenv(faults.LAUNCH_ENV_VAR, "1")
    faults.install({"faults": [
        {"op": "io_error", "target": "checkpoint", "at_write": 1,
         "at_launch": 1}]})
    with pytest.raises(OSError, match="injected"):
        save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    monkeypatch.setenv(faults.LAUNCH_ENV_VAR, "2")
    faults.install({"faults": [
        {"op": "io_error", "target": "checkpoint", "at_write": 1,
         "at_launch": 1}]})
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    assert verify_checkpoint(ck)["crc_verified"]


def test_fuzz_spec_deterministic_and_valid():
    """Same (seed, index) -> same plan, every plan validates, and the
    stream covers all four crash-point shapes within a modest sweep."""
    kinds = set()
    for i in range(64):
        spec = faults.fuzz_spec(20260804, i)
        assert spec == faults.fuzz_spec(20260804, i)
        FaultPlan(spec)                    # validates
        ops = tuple(sorted(f["op"] for f in spec["faults"]))
        kinds.add(ops)
    flat = {op for ops in kinds for op in ops}
    assert {"kill", "kill_event", "io_error"} <= flat
    assert flat & {"torn_write", "bit_flip"}
    # a different seed reshuffles the stream
    assert any(faults.fuzz_spec(1, i) != faults.fuzz_spec(2, i)
               for i in range(8))


def test_fuzz_env_var_parses_seed_and_index(monkeypatch):
    faults.clear()
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    monkeypatch.setenv(faults.FUZZ_ENV_VAR, "77:3")
    plan = faults.fault_plan()
    assert plan is not None
    assert [f["op"] for f in plan.faults] == [
        f["op"] for f in faults.fuzz_spec(77, 3)["faults"]]
    faults.clear()
    monkeypatch.setenv(faults.FUZZ_ENV_VAR, "not-a-spec")
    with pytest.raises(FaultPlanError, match="seed:index"):
        faults.fault_plan()
    faults.clear()


def test_io_error_and_delay_faults(tmp_path, data):
    """io_error surfaces as OSError from the save; io_delay stalls it."""
    ck = str(tmp_path / "io.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "io_error", "target": "checkpoint", "at_write": 1}]})
    with pytest.raises(OSError, match="injected"):
        save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install({"faults": [
        {"op": "io_delay", "target": "checkpoint", "seconds": 0.2,
         "at_write": 1}]})
    t0 = time.perf_counter()
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    assert time.perf_counter() - t0 >= 0.2
    # write #2 has no fault: fast and intact
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    assert verify_checkpoint(ck)["crc_verified"]


def test_torn_write_fault_detected(tmp_path):
    """A torn write (file truncated after the atomic rename) leaves a
    file the loaders refuse - never a silent partial resume."""
    ck = str(tmp_path / "torn.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "torn_write", "target": "checkpoint", "at_write": 1,
         "keep_fraction": 0.5}]})
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install(None)
    with pytest.raises(Exception):       # truncated zip container
        read_checkpoint_meta(ck)
    with pytest.raises(Exception):
        verify_checkpoint(ck)


def test_bit_flip_fault_caught_by_crc(tmp_path):
    """bit_flip corrupts AFTER the CRCs are computed - exactly the silent
    corruption the integrity format exists to catch, surfaced as the
    typed CheckpointCorruptError by both verify and load."""
    ck = str(tmp_path / "flip.npz")
    carry = _carry()
    faults.install({"faults": [
        {"op": "bit_flip", "target": "checkpoint", "at_write": 1,
         "leaf": "leaf_0"}]})
    save_checkpoint(ck, carry, _cfg(), fingerprint="f")
    faults.install(None)
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        verify_checkpoint(ck)
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        load_checkpoint(ck, carry)


# ---------------------------------------------------------------------------
# divergence sentinel
# ---------------------------------------------------------------------------

def test_sentinel_rewind_recovers_finite_posterior(tmp_path, data):
    """An injected mid-run divergence (poison_state) trips the sentinel:
    the chain rewinds to the last checkpoint with a re-lineaged key and
    escalated jitter, and the fit completes with a finite posterior and
    a zero non-finite health count (the garbage chunks were discarded,
    not accumulated).  Documented NON-bit-exact vs an undiverged run."""
    ck = str(tmp_path / "sent.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1)
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    res = fit(data, cfg)
    assert res.sentinel_rewinds == 1
    assert np.isfinite(res.Sigma).all()
    assert float(np.asarray(res.stats.nonfinite_count)) == 0.0
    assert float(np.asarray(res.stats.acc_nonfinite)) == 0.0


def test_sentinel_abort_without_checkpoint(data):
    """No checkpoint -> nothing to rewind to: the sentinel aborts with
    the typed error at the boundary where divergence was detected,
    instead of completing with garbage."""
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    with pytest.raises(ChainDivergedError) as ei:
        fit(data, _cfg())
    assert ei.value.iteration == 24          # poisoned 16, detected at 24
    assert ei.value.rewinds == 0


def test_sentinel_off_preserves_old_behavior(data):
    """sentinel='off': the divergence runs to completion and poisons the
    result (the pre-sentinel behavior, kept reachable on purpose - it is
    what the sentinel's default protects against)."""
    faults.install({"faults": [{"op": "poison_state", "at_iteration": 16}]})
    res = fit(data, _cfg(sentinel="off"))
    assert float(np.asarray(res.stats.nonfinite_count)) > 0


def test_sentinel_rewind_budget_exhaustion(tmp_path, data):
    """Every retry re-diverging must exhaust the budget and raise - not
    loop forever.  poison_state faults at every post-rewind boundary."""
    ck = str(tmp_path / "budget.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1,
               sentinel_max_rewinds=1)
    faults.install({"faults": [
        {"op": "poison_state", "at_iteration": 16},
        {"op": "poison_state", "at_iteration": 16}]})
    with pytest.raises(ChainDivergedError, match="budget"):
        fit(data, cfg)


def test_healthy_chain_bitwise_unaffected_by_sentinel(tmp_path, data):
    """The sentinel only READS the per-chunk stats: a healthy chain's
    result is bit-identical with the sentinel on (default) and off."""
    res_on = fit(data, _cfg())
    res_off = fit(data, _cfg(sentinel="off"))
    np.testing.assert_array_equal(res_on.sigma_blocks, res_off.sigma_blocks)
    assert res_on.sentinel_rewinds == 0


# ---------------------------------------------------------------------------
# supervised runs (real CLI children, real SIGKILL)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def data_file(tmp_path_factory, data):
    d = tmp_path_factory.mktemp("chaos")
    p = str(d / "Y.npy")
    np.save(p, data)
    return p


def test_supervised_kill_resume_bit_exact(tmp_path, data_file):
    """THE acceptance demo: DCFM_FAULT_PLAN SIGKILLs the child at a
    mid-run iteration under `dcfm-tpu fit --supervise`; the supervisor
    resumes it and the final Sigma is BIT-IDENTICAL to the uninterrupted
    run's."""
    ref = str(tmp_path / "ref.npy")
    proc = _cli_fit(data_file, ref, [], _child_env())
    assert proc.returncode == 0, proc.stderr

    out = str(tmp_path / "sup.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [{"op": "kill", "at_iteration": 16,
                        "when": "post_save"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1", "--keep-last", "2",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stderr.strip().splitlines()[-1])
    assert report["launches"] == 2           # died once, resumed once
    assert report["deaths"][0][0] == -9      # a real SIGKILL
    assert report["final_iteration"] == 32
    np.testing.assert_array_equal(np.load(ref), np.load(out))


def test_supervised_poison_iteration_aborts(tmp_path, data_file):
    """A pre-save kill pins the checkpoint below the trigger: every
    relaunch dies at the same iteration.  The supervisor must abort with
    the typed PoisonedRunError after the second same-iteration death -
    exactly 2 launches, never a crash-loop."""
    out = str(tmp_path / "p.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [{"op": "kill", "at_iteration": 16,
                        "when": "pre_save"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 3, proc.stderr
    err = json.loads(proc.stderr.strip().splitlines()[-1])
    assert err["error"] == "PoisonedRunError"
    assert err["iteration"] == 8             # the save before the kill point
    assert err["checkpoint"] == ck
    assert proc.stderr.count("launch #") == 2


def test_supervised_corrupt_checkpoint_falls_back(tmp_path, data_file):
    """Acceptance criterion: a corrupted latest checkpoint is detected by
    CRC and the supervisor resumes from the previous retained one.  The
    plan bit-flips the save at iteration 16 and kills the child there;
    the supervisor demotes the corrupt file, promotes .bak1 (iteration
    8), and the run still completes bit-identically."""
    ref = str(tmp_path / "ref.npy")
    proc = _cli_fit(data_file, ref, [], _child_env())
    assert proc.returncode == 0, proc.stderr

    out = str(tmp_path / "c.npy")
    ck = str(tmp_path / "ck.npz")
    plan = {"faults": [
        {"op": "kill", "at_iteration": 16, "when": "post_save"},
        {"op": "bit_flip", "target": "checkpoint", "at_write": 2,
         "path_re": "ck.npz$"}]}
    proc = _cli_fit(
        data_file, out,
        ["--checkpoint", ck, "--checkpoint-every", "1", "--keep-last", "2",
         "--supervise", "--supervise-backoff", "0.05"],
        _child_env(plan))
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stderr.strip().splitlines()[-1])
    # TWO fallbacks: the mid-run one (kill at the flipped write) and the
    # exit pass - this plan's per-process write counter flips child 3's
    # write #2, i.e. the FINAL save, and the supervisor must leave the
    # live slot verified (newest clean generation promoted) on the way
    # out so a future resume doesn't trip over bad bytes
    assert report["corrupt_fallbacks"] == 2
    assert report["final_iteration"] == 24      # newest CLEAN generation
    assert "promoted retained checkpoint" in proc.stderr
    np.testing.assert_array_equal(np.load(ref), np.load(out))


@pytest.mark.slow
def test_supervise_api_returns_full_fitresult(tmp_path, data):
    """The API entry point: supervise(Y, cfg) runs the chain in children
    through an injected SIGKILL and returns a real FitResult whose Sigma
    is bit-identical to an in-process uninterrupted fit."""
    from dcfm_tpu.resilience import supervise

    res_ref = fit(data, _cfg())
    ck = str(tmp_path / "api.npz")
    cfg = _cfg(checkpoint_path=ck, checkpoint_every_chunks=1)
    env_plan = json.dumps(
        {"faults": [{"op": "kill", "at_iteration": 16,
                     "when": "post_save"}]})
    old = os.environ.get(faults.ENV_VAR)
    os.environ[faults.ENV_VAR] = env_plan
    try:
        # the PARENT must not execute the plan (it would SIGKILL the test
        # process at its no-op resume): neutralize it in-process while
        # the children inherit it from the environment
        faults.install({"faults": []})
        res = supervise(data, cfg, backoff_base=0.05)
    finally:
        if old is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = old
    np.testing.assert_array_equal(res.sigma_blocks, res_ref.sigma_blocks)
    np.testing.assert_array_equal(res.Sigma, res_ref.Sigma)


def test_esig_includes_acc_start():
    """ADVICE r5 regression (unit half; the 2-process half is the --esig
    multihost demo): two sidecar eligibility results agreeing on
    iteration/kind/writer-count but starting their accumulation windows
    at different iterations must produce DIFFERENT unanimity
    signatures, so the collective gate refuses the pair instead of
    letting each host divide by its own n_saved."""
    from dcfm_tpu.api import _sidecar_esig

    src = ("set", (2, ["a.proc0-of-2", "a.proc1-of-2"], 4))
    e0 = _sidecar_esig((src, 4, 0))
    e1 = _sidecar_esig((src, 4, 2))
    assert e0.shape == (4,) and e0[3] == 0 and e1[3] == 2
    assert not np.array_equal(e0, e1)        # the gate refuses the pair
    assert np.array_equal(e0, _sidecar_esig((src, 4, 0)))
    assert (_sidecar_esig(None) == -1).all()


# ---------------------------------------------------------------------------
# pod supervision (coordinated stop, unanimity pre-pass, watchdog)
# ---------------------------------------------------------------------------

def test_supervise_pod_coordinated_stop_and_poison(tmp_path):
    """When one 'host' dies, its sibling - parked like a process blocked
    in a collective - must be REAPED promptly (coordinated stop), and
    two consecutive no-progress pod deaths must abort with the typed
    poison error, not crash-loop."""
    from dcfm_tpu.resilience.supervisor import (
        PoisonedRunError, supervise_pod)

    def spawn(attempt):
        return [
            subprocess.Popen([sys.executable, "-c",
                              "import sys; sys.exit(7)"]),
            subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(120)"]),
        ]

    t0 = time.perf_counter()
    with pytest.raises(PoisonedRunError):
        supervise_pod(spawn, checkpoint_path=str(tmp_path / "pod.ck"),
                      num_processes=2, backoff_base=0.01,
                      poison_deaths=2, grace=2.0, log=lambda m: None)
    # 2 launches, each reaped within ~grace - nowhere near the sleeps a
    # hung wait-for-everyone would cost
    assert time.perf_counter() - t0 < 40


def test_supervise_pod_watchdog_raises_typed_hang(tmp_path):
    """A launch where nothing dies and nothing finishes is a deadlock:
    the watchdog must kill the pod and raise the typed error instead of
    waiting forever (the bound the fuzz harness relies on)."""
    from dcfm_tpu.resilience.supervisor import PodHangError, supervise_pod

    def spawn(attempt):
        return [subprocess.Popen([sys.executable, "-c",
                                  "import time; time.sleep(120)"])
                for _ in range(2)]

    t0 = time.perf_counter()
    with pytest.raises(PodHangError, match="watchdog"):
        supervise_pod(spawn, checkpoint_path=str(tmp_path / "pod.ck"),
                      num_processes=2, launch_timeout=1.5, grace=1.0,
                      log=lambda m: None)
    assert time.perf_counter() - t0 < 30


def _save_iter(slot, iteration, keep_last=2):
    c = _CarryLike(a=np.arange(64.0), b=np.ones((8, 8)),
                   iteration=np.int32(iteration))
    save_checkpoint(slot, c, _cfg(), fingerprint="f", keep_last=keep_last)


def _rot_payload(path):
    """Rot real PAYLOAD bytes of a checkpoint npz, in place.

    A fixed file offset (the old ``size // 2``) is layout-sensitive:
    np.savez 64-aligns members with local-header extra padding, so a
    config-growth that resizes ``__meta__`` can silently move the
    midpoint into structural bytes the zip reader never looks at - and
    then nothing actually rotted (the arrays restore bit-identical).
    Parse the archive and hit the middle of the largest leaf's DATA
    instead: bytes that are CRC-recorded at save and restored at load.
    Opens ``r+b`` so hardlinked retention copies share the damage, like
    real in-place media rot.
    """
    import struct
    import zipfile

    with zipfile.ZipFile(path) as z:
        zi = max((i for i in z.infolist()
                  if i.filename.startswith("leaf_")),
                 key=lambda i: i.compress_size)
        off, csize = zi.header_offset, zi.compress_size
    with open(path, "r+b") as f:
        f.seek(off + 26)                  # local header: fnlen, extralen
        fnlen, extralen = struct.unpack("<HH", f.read(4))
        f.seek(off + 30 + fnlen + extralen + csize // 2)
        f.write(b"\xff" * 8)


def test_unanimous_pre_pass_promotes_common_generation(tmp_path):
    """A kill between two processes' saves leaves the newest generation
    on only one host.  The pod pre-pass must promote the newest
    generation held by BOTH (here 16), discarding host 0's lone 24 -
    per-slot newest-clean promotion would hand the children a mixed
    state the collective gate refuses forever."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    _save_iter(s0, 16)
    _save_iter(s0, 24)            # slot0: live 24, bak1 16
    _save_iter(s1, 16)            # slot1: the 24 save never landed
    rep = SuperviseReport()
    it = _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None)
    assert it == 16
    assert read_checkpoint_meta(s0)["iteration"] == 16
    assert read_checkpoint_meta(s1)["iteration"] == 16


def test_unanimous_pre_pass_demotes_corrupt_then_promotes(tmp_path):
    """CRC corruption on ONE host's newest file demotes that generation
    there, which breaks its unanimity - both hosts land on the previous
    generation."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    for s in (s0, s1):
        _save_iter(s, 16)
        _save_iter(s, 24)
    _rot_payload(s1)                 # silent media corruption on host 1
    rep = SuperviseReport()
    it = _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None)
    assert it == 16
    assert rep.corrupt_fallbacks == 1
    assert os.path.exists(s1 + ".corrupt")
    assert read_checkpoint_meta(s0)["iteration"] == 16
    assert read_checkpoint_meta(s1)["iteration"] == 16


def test_pod_progress_sees_through_mixed_live_files(tmp_path):
    """Death accounting must not read -1 from the MIXED live state a
    between-saves kill routinely leaves: two such deaths in a row would
    satisfy the poison check's same-iteration rule (-1 == -1) and abort
    a pod that makes real progress between crashes.  _pod_progress
    intersects the retention CHAINS, so the unanimously-held generation
    (what the next launch actually resumes) is the measure."""
    from dcfm_tpu.resilience.supervisor import _pod_progress
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    _save_iter(s0, 16)
    _save_iter(s0, 24)            # slot0 live 24, bak1 16
    _save_iter(s1, 16)            # slot1 live 16: mixed live set
    assert _pod_progress(base, 2) == 16
    # nothing at all -> genuinely no progress
    assert _pod_progress(str(tmp_path / "none.ck"), 2) == -1


class _FakeProc:
    """poll()-compatible stand-in: exits 0 once ``done_after`` seconds
    have passed since construction."""

    def __init__(self, done_after):
        self._t0 = time.perf_counter()
        self._done_after = done_after

    def poll(self):
        return 0 if time.perf_counter() - self._t0 >= self._done_after \
            else None

    def terminate(self):
        self._done_after = 0.0

    def kill(self):
        self._done_after = 0.0

    def wait(self):
        return 0


def test_await_pod_watchdog_resets_on_checkpoint_progress():
    """A healthy launch LONGER than the watchdog must not be reaped as
    a hang while its checkpoint iteration keeps advancing: the probe's
    advances reset the deadline, so the watchdog only needs to exceed
    one boundary-to-boundary interval, not the whole run."""
    from dcfm_tpu.resilience.supervisor import _await_pod

    t0 = time.perf_counter()

    def progress():
        # "checkpoint" advances every ~0.4s, like boundary saves
        return int((time.perf_counter() - t0) / 0.4)

    rc = _await_pod([_FakeProc(2.5)], launch_timeout=1.2, grace=0.1,
                    log=lambda m: None, progress_fn=progress)
    assert rc == 0


def test_watchdog_probe_counts_single_slot_advance(tmp_path):
    """The liveness score must MOVE when one slow host's own file
    advances, even while a finished peer's file is parked at a higher
    iteration: _progress_iteration reads that mixed live set as -1, and
    a max would sit at the parked value - either way a healthy re-run
    window longer than the watchdog would be reaped as a 'hang'."""
    from dcfm_tpu.resilience.supervisor import (
        _progress_iteration, _watchdog_progress)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    _save_iter(proc_path(base, 0, 2), 40)   # finished host, parked
    _save_iter(proc_path(base, 1, 2), 20)   # slow host, still advancing
    assert _progress_iteration(base) == -1  # no agreeing set
    s0 = _watchdog_progress(base, 2)
    _save_iter(proc_path(base, 1, 2), 24)   # the advance the probe needs
    s1 = _watchdog_progress(base, 2)
    assert s1 > s0                          # the deadline resets
    assert _watchdog_progress(str(tmp_path / "none.ck"), 2) == -1


def test_unanimity_pre_pass_demotes_stale_other_count_sets(tmp_path):
    """A corrupt ``.procK-of-M`` file from an EARLIER process count must
    be demoted by the pod pre-pass exactly as the single-host pass
    would: discovery's most-progress rule can select the stale set for
    a topology-flexible resume, and leaving the corrupt member in place
    would make that resume fail on every relaunch."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    # current topology: 2 processes at iteration 8
    for i in range(2):
        _save_iter(proc_path(base, i, 2), 8)
    # stale, more-progressed 3-process set with one corrupt member
    for i in range(3):
        _save_iter(proc_path(base, i, 3), 24)
    stale = proc_path(base, 1, 3)
    _rot_payload(stale)
    rep = SuperviseReport()
    _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None)
    assert rep.corrupt_fallbacks == 1
    assert os.path.exists(stale + ".corrupt")
    assert read_checkpoint_meta(proc_path(base, 0, 2))["iteration"] == 8


def test_promotion_keeps_retention_chain_gapless(tmp_path):
    """Promoting a .bakK generation into the live slot must keep it at
    its .bakK position (hardlink, not os.replace): after a promotion, a
    SECOND failure that corrupts the promoted live file must still find
    the promoted generation (and everything older) in the chain - and
    the cross-slot unanimity intersection must still see it at its
    retained position - instead of orphaning a resumable pod to a
    fresh start."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    for it in (8, 16, 24):
        _save_iter(s0, it, keep_last=3)   # live 24, bak1 16, bak2 8
    for it in (8, 16):
        _save_iter(s1, it, keep_last=3)   # live 16, bak1 8
    rep = SuperviseReport()
    assert _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None) == 16
    # the promotion left the chain gapless: bak1 still holds gen 16
    assert os.path.exists(s0 + ".bak1")
    # Second failure: host 0's gen-16 bytes rot (in-place corruption -
    # live and .bak1 share the inode, exactly like the keep_last
    # rotation's hardlinks, so BOTH copies of 16 die).  Pre-fix the
    # bak1 HOLE hid gen 8 behind it and the pod was orphaned to a
    # fresh start; with the gapless chain it falls back to 8.
    _rot_payload(s0)
    rep2 = SuperviseReport()
    it = _ensure_unanimous_checkpoint(base, 2, rep2, lambda m: None)
    assert it == 8                        # recovered, not orphaned
    assert not os.path.exists(s0 + ".orphan")
    assert read_checkpoint_meta(s0)["iteration"] == 8
    assert read_checkpoint_meta(s1)["iteration"] == 8


def test_await_pod_watchdog_resets_on_clean_exit():
    """A process exiting 0 is progress: the watchdog deadline must reset
    so a slower sibling legitimately re-running a lost window is not
    reaped as a 'hang' - the deadline bounds time since the last
    observable event (launch or a clean exit), not the whole launch.
    Here the sibling needs 2.2s against a 1.5s watchdog; only the reset
    at the fast process's 1.0s exit lets the launch succeed."""
    from dcfm_tpu.resilience.supervisor import _await_pod

    rc = _await_pod([_FakeProc(1.0), _FakeProc(2.2)],
                    launch_timeout=1.5, grace=0.1, log=lambda m: None)
    assert rc == 0


def test_unanimous_pre_pass_orphans_disjoint_state(tmp_path):
    """No generation held by all hosts: the live files are set aside so
    every host's discovery starts FRESH deterministically (a mixed live
    set would make a strict resume refuse on every relaunch)."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    _save_iter(s0, 24, keep_last=1)
    _save_iter(s1, 16, keep_last=1)
    rep = SuperviseReport()
    it = _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None)
    assert it == -1
    assert not os.path.exists(s0) and not os.path.exists(s1)
    assert os.path.exists(s0 + ".orphan") and os.path.exists(s1 + ".orphan")


def test_crash_fuzz_smoke_single_process(tmp_path, data_file):
    """CI smoke of the randomized crash-point harness (the full >= 50
    point 2-process sweep is slow-marked in test_multihost.py): 8
    seeded fuzz points through the REAL supervised CLI - kills pre/post
    save, torn/bit-flipped/failing checkpoint writes.  Every outcome
    must be a clean BIT-EXACT resume or a clean typed refusal; any
    other exit is a harness failure."""
    seed = 20260804
    ref = str(tmp_path / "ref.npy")
    proc = _cli_fit(data_file, ref, [], _child_env())
    assert proc.returncode == 0, proc.stderr
    ref_sigma = np.load(ref)

    outcomes = []
    for i in range(8):
        spec = faults.fuzz_spec(seed, i, boundaries=(8, 16, 24, 32),
                                max_writes=4, nproc=1, events=())
        out = str(tmp_path / f"fz{i}.npy")
        ck = str(tmp_path / f"fz{i}.ck.npz")
        env = _child_env(spec)
        env["DCFM_FAULT_PROCESS"] = "0"
        proc = _cli_fit(
            data_file, out,
            ["--checkpoint", ck, "--checkpoint-every", "1",
             "--keep-last", "2", "--supervise",
             "--supervise-backoff", "0.05",
             "--supervise-max-retries", "4",
             "--supervise-poison-deaths", "3",
             "--supervise-watchdog", "420"],
            env)
        if proc.returncode == 0:
            np.testing.assert_array_equal(
                np.load(out), ref_sigma,
                err_msg=f"fuzz point {i}: resumed Sigma diverged")
            outcomes.append("clean")
        elif proc.returncode == 3:
            err = json.loads(proc.stderr.strip().splitlines()[-1])
            assert err["error"] in ("PoisonedRunError",
                                    "RetriesExhaustedError"), (i, err)
            outcomes.append(err["error"])
        else:
            pytest.fail(f"fuzz point {i}: unclean exit "
                        f"{proc.returncode}\n{proc.stderr[-2000:]}")
    assert "clean" in outcomes       # the sweep exercises real resumes


def test_supervise_requires_checkpoint(data):
    from dcfm_tpu.resilience import supervise

    with pytest.raises(ValueError, match="checkpoint_path"):
        supervise(data, _cfg())
    with pytest.raises(ValueError, match="full"):
        supervise(data, _cfg(checkpoint_path="x", checkpoint_mode="light"))


def test_supervise_report_attached_to_fitresult(tmp_path, data):
    """API callers see the supervision telemetry, not just the CLI's
    stderr JSON: a crash-free supervise() attaches a report with one
    launch and no deaths."""
    from dcfm_tpu.resilience import supervise

    ck = str(tmp_path / "rep.npz")
    res = supervise(data, _cfg(checkpoint_path=ck), backoff_base=0.05)
    rep = res.supervise_report
    assert rep is not None and rep.launches == 1 and rep.deaths == []
    assert rep.final_iteration == 32
    # a plain fit has none
    assert fit(data, _cfg()).supervise_report is None


def test_demotion_hole_does_not_hide_older_generations(tmp_path):
    """Demoting a corrupt MIDDLE .bakK must not hide the generations
    behind it: after .bak1 is demoted and the live file later rots too,
    the pre-pass must still find the clean .bak2 generation instead of
    orphaning a resumable pod to a fresh start."""
    from dcfm_tpu.resilience.supervisor import (
        SuperviseReport, _ensure_unanimous_checkpoint)
    from dcfm_tpu.utils.checkpoint import proc_path

    base = str(tmp_path / "pod.ck")
    s0, s1 = proc_path(base, 0, 2), proc_path(base, 1, 2)
    for s in (s0, s1):
        for it in (8, 16, 24):
            _save_iter(s, it, keep_last=3)   # live 24, bak1 16, bak2 8

    _rot_payload(s0 + ".bak1")               # middle generation rots
    rep = SuperviseReport()
    assert _ensure_unanimous_checkpoint(base, 2, rep, lambda m: None) == 24
    assert os.path.exists(s0 + ".bak1.corrupt")   # demoted: chain has a hole
    # second failure: host 0's live file rots as well (bak1@16 on host 0
    # is gone, so 16 is not unanimous; 8 must still be reachable PAST
    # the .bak1 hole)
    _rot_payload(s0)
    rep2 = SuperviseReport()
    it = _ensure_unanimous_checkpoint(base, 2, rep2, lambda m: None)
    assert it == 8
    assert not os.path.exists(s0 + ".orphan")
    assert read_checkpoint_meta(s0)["iteration"] == 8
    assert read_checkpoint_meta(s1)["iteration"] == 8
