"""RNG key-lineage tests (SURVEY.md section 5 "Race detection" analogue,
section 7 "Hard parts: RNG discipline").

The TPU analogue of a data race is PRNG-key reuse: two sites (or two
shards, or two iterations, or two chains) drawing from the same stream
silently correlates what the model assumes independent.  These tests pin
the key-derivation contract directly, complementing the mesh==vmap
equivalence tests that pin it end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dcfm_tpu.models.conditionals import (
    _SITE_LAM, _SITE_PRIOR, _SITE_PS, _SITE_X, _SITE_Z, _shard_keys)
from dcfm_tpu.models.adapt import _SITE_ADAPT
from dcfm_tpu.models.sampler import chain_keys


def _key_data(k):
    return np.asarray(jax.random.key_data(k)).reshape(-1)


def test_site_ids_are_distinct():
    sites = [_SITE_Z, _SITE_X, _SITE_LAM, _SITE_PRIOR, _SITE_PS, _SITE_ADAPT]
    assert len(set(sites)) == len(sites)


def test_site_keys_differ_per_site_and_shard():
    key = jax.random.key(0)
    seen = set()
    for site in (_SITE_Z, _SITE_X, _SITE_LAM, _SITE_PRIOR, _SITE_PS,
                 _SITE_ADAPT):
        site_key = jax.random.fold_in(key, site)
        seen.add(tuple(_key_data(site_key)))
        shard_keys = _shard_keys(site_key, 0, 4)
        for g in range(4):
            seen.add(tuple(_key_data(shard_keys[g])))
    # 6 site keys + 6*4 shard keys, all distinct
    assert len(seen) == 6 + 6 * 4


def test_shard_keys_depend_on_global_not_local_index():
    """Device d's local shard i must draw the stream of GLOBAL shard
    offset+i: the mesh layout derives identical streams to the vmap layout."""
    site_key = jax.random.fold_in(jax.random.key(7), _SITE_Z)
    all_keys = _shard_keys(site_key, 0, 8)          # vmap layout: shards 0-7
    dev1_keys = _shard_keys(site_key, 4, 4)         # mesh device 1: shards 4-7
    np.testing.assert_array_equal(
        jax.random.key_data(all_keys[4:]), jax.random.key_data(dev1_keys))


def test_iteration_keys_derive_from_global_index():
    """run_chunk folds the chunk key with the GLOBAL iteration index, so
    chunking/resume cannot change the chain (test_chunked_run_matches_
    single_scan pins this end-to-end; here: the streams really differ per
    iteration and match across chunk boundaries)."""
    key = jax.random.key(3)
    # chunk A covering iterations 0..9, chunk B covering 5..14
    a = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(0, 10))
    b = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(5, 15))
    np.testing.assert_array_equal(
        jax.random.key_data(a[5:]), jax.random.key_data(b[:5]))
    flat = np.asarray(jax.random.key_data(a)).reshape(10, -1)
    assert len({tuple(r) for r in flat}) == 10


def test_chain_keys_distinct_and_shared_across_layouts():
    key = jax.random.key(11)
    ks = chain_keys(key, 4)
    flat = np.asarray(jax.random.key_data(ks)).reshape(4, -1)
    assert len({tuple(r) for r in flat}) == 4
    # the derivation is fold_in(key, c) - the contract both the local vmap
    # path and the mesh path rely on for chain-for-chain equality
    for c in range(4):
        np.testing.assert_array_equal(
            jax.random.key_data(ks[c]),
            jax.random.key_data(jax.random.fold_in(key, c)))


def test_x_site_key_is_shard_independent():
    """The shared factor X must be drawn from the UNFOLDED site key so every
    device samples the identical replicated X (conditionals.py docstring);
    pin that the X site stream differs from every per-shard stream."""
    key = jax.random.key(0)
    x_key = _key_data(jax.random.fold_in(key, _SITE_X))
    for site in (_SITE_Z, _SITE_LAM, _SITE_PRIOR, _SITE_PS):
        sk = jax.random.fold_in(key, site)
        for g in range(4):
            assert tuple(_key_data(_shard_keys(sk, 0, 4)[g])) != tuple(x_key)
