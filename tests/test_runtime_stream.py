"""Streamed accumulator fetch (runtime/pipeline.StreamingFetcher).

The contract under test: the double-buffered per-boundary snapshot
stream is a PURE overlap optimization - every bit of the result
(quant8 panels, per-panel scales, posterior-SD panels, assembled
Sigma, exported artifact) is identical to the post-hoc fetch, under
every pipeline disturbance the runtime supports:

* plain chunked runs, single-device and mesh layouts;
* bounded-buffer SKIPS (both in-flight slots busy -> the boundary's
  snapshot is dropped, never blocking the chain);
* light-checkpoint resume (acc_start > 0 window divisors);
* a sentinel rewind mid-run (the window moves; stale queued snapshots
  must be superseded, never summed);
* a real SIGKILL inside the streaming window + supervised resume
  (the PR 4/5 fault seams, via the new ``stream_submit`` event);
* drain commits through OWNED host copies (the PR-1/PR-5
  use-after-free class: deleting the device-side snapshot after the
  drain must not perturb the landed bytes).

Plus the free fit->export path: panels streamed straight into the
serve artifact's memmap layout are bitwise the post-hoc export.
"""

import json
import os
import time

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.config import validate
from dcfm_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def data():
    Y, _ = make_synthetic(n=60, p=96, k_true=3, seed=0)
    return Y


def _cfg(stream="auto", *, posterior_sd=False, mesh=0, chunk=30,
         mcmc=40, **kw):
    return FitConfig(
        model=ModelConfig(num_shards=8, factors_per_shard=3, rho=0.8,
                          posterior_sd=posterior_sd),
        run=RunConfig(burnin=40, mcmc=mcmc, thin=2, seed=0,
                      chunk_size=chunk),
        backend=BackendConfig(fetch_dtype="quant8", fetch_stream=stream,
                              mesh_devices=mesh),
        **kw)


def _assert_bitwise(r_on, r_off, sd=False):
    assert r_on.stream_stats is not None and r_on.stream_stats["streamed"]
    assert r_off.stream_stats is None
    np.testing.assert_array_equal(r_on._q8_panels, r_off._q8_panels)
    np.testing.assert_array_equal(r_on._q8_scales, r_off._q8_scales)
    np.testing.assert_array_equal(r_on.Sigma, r_off.Sigma)
    if sd:
        np.testing.assert_array_equal(r_on._sd_q8_panels,
                                      r_off._sd_q8_panels)
        np.testing.assert_array_equal(r_on._sd_q8_scales,
                                      r_off._sd_q8_scales)
        np.testing.assert_array_equal(r_on.Sigma_sd, r_off.Sigma_sd)


# ---------------------------------------------------------------------------
# bitwise identity: streamed == post-hoc
# ---------------------------------------------------------------------------

def test_streamed_bitwise_single_device_with_sd(data):
    r_on = fit(data, _cfg("auto", posterior_sd=True))
    r_off = fit(data, _cfg("off", posterior_sd=True))
    _assert_bitwise(r_on, r_off, sd=True)
    # burn-in boundaries (no saved draws) are skipped, not streamed:
    # chunks of 30 over 40+40 iters -> boundaries at 30 (burn-in), 60, 80
    assert r_on.stream_stats["snapshots"] == 2
    # telemetry shape: one drain sample per snapshot, exposed recorded
    assert len(r_on.stream_stats["chunk_fetch_s"]) == 2
    assert r_on.phase_seconds["exposed_fetch_s"] >= 0.0
    # post-hoc runs expose their whole fetch by definition
    assert (r_off.phase_seconds["exposed_fetch_s"]
            == r_off.phase_seconds["fetch_s"])


def test_streamed_bitwise_mesh(data):
    r_on = fit(data, _cfg("auto", mesh=2))
    r_off = fit(data, _cfg("off", mesh=2))
    _assert_bitwise(r_on, r_off)


def test_streamed_single_chunk_schedule(data):
    # chunk_size=0 -> one chunk; the only boundary is final and streams
    r_on = fit(data, _cfg("auto", chunk=0))
    r_off = fit(data, _cfg("off", chunk=0))
    _assert_bitwise(r_on, r_off)
    assert r_on.stream_stats["snapshots"] == 1


def test_bounded_buffer_skips_but_stays_bitwise(data, monkeypatch):
    """Both double-buffer slots busy -> the boundary snapshot is
    SKIPPED (the chain is never blocked), and the final result is still
    bitwise the post-hoc fetch.  Forced by slowing the drain."""
    import dcfm_tpu.runtime.pipeline as pl
    real = pl.quant8_drain

    def slow_drain(slices, shape, out=None):
        time.sleep(0.2)
        return real(slices, shape, out)

    monkeypatch.setattr(pl, "quant8_drain", slow_drain)
    r_on = fit(data, _cfg("auto", chunk=10))   # 8 boundaries, 4 streamable
    monkeypatch.setattr(pl, "quant8_drain", real)
    r_off = fit(data, _cfg("off", chunk=10))
    assert r_on.stream_stats["skipped"] >= 1
    _assert_bitwise(r_on, r_off)


def test_streamed_light_resume_window(data, tmp_path):
    """acc_start > 0: a light-checkpoint resume restarts accumulation
    mid-chain, so the streamed window divisor differs from 1/num_saved -
    streamed and post-hoc resumes must still agree bitwise."""
    results = {}
    for stream in ("auto", "off"):
        ck = str(tmp_path / f"light_{stream}.npz")
        fit(data, _cfg("off", mcmc=20, checkpoint_path=ck,
                       checkpoint_mode="light"))
        results[stream] = fit(
            data, _cfg(stream, mcmc=40, checkpoint_path=ck,
                       checkpoint_mode="light", resume=True))
    _assert_bitwise(results["auto"], results["off"])


def test_noop_finished_resume_does_not_stream(data, tmp_path):
    """Resuming a FINISHED checkpoint executes zero chunks: the streamer
    never engages and the post-hoc fetch serves the materialization
    (this is exactly how supervise() materializes its FitResult).  A
    requested stream_artifact still lands - via the post-hoc export
    fallback - and is bitwise the streamed one."""
    ck = str(tmp_path / "done.npz")
    a1 = str(tmp_path / "a_run")
    a2 = str(tmp_path / "a_noop")
    ref = fit(data, _cfg("auto", checkpoint_path=ck, stream_artifact=a1))
    noop = fit(data, _cfg("auto", checkpoint_path=ck, resume=True,
                          stream_artifact=a2))
    assert noop.stream_stats is None
    assert noop.artifact_path == a2
    np.testing.assert_array_equal(ref.Sigma, noop.Sigma)
    with open(os.path.join(a1, "mean_q8.bin"), "rb") as f:
        b1 = f.read()
    with open(os.path.join(a2, "mean_q8.bin"), "rb") as f:
        b2 = f.read()
    assert b1 == b2


def test_sentinel_rewind_resets_stream_window(data, tmp_path):
    """A mid-run divergence rewind moves acc_start; the streamer's
    window must follow and stale queued snapshots must be superseded.
    Injected via the deterministic poison_state fault under identical
    plans, so streamed and post-hoc rewound runs are comparable
    bitwise."""
    results = {}
    for stream in ("auto", "off"):
        ck = str(tmp_path / f"rw_{stream}.npz")
        faults.install({"faults": [
            {"op": "poison_state", "at_iteration": 60}]})
        try:
            results[stream] = fit(
                data, _cfg(stream, chunk=10, checkpoint_path=ck,
                           checkpoint_every_chunks=1,
                           checkpoint_keep_last=2, sentinel="rewind"))
        finally:
            faults.install(None)
        assert results[stream].sentinel_rewinds == 1
    _assert_bitwise(results["auto"], results["off"])


# ---------------------------------------------------------------------------
# streamed serve artifact: fit -> export is free and bitwise
# ---------------------------------------------------------------------------

def test_stream_artifact_bitwise_vs_posthoc_export(data, tmp_path):
    a_stream = str(tmp_path / "streamed")
    a_posthoc = str(tmp_path / "posthoc")
    r_on = fit(data, _cfg("auto", posterior_sd=True,
                          stream_artifact=a_stream))
    r_off = fit(data, _cfg("off", posterior_sd=True))
    art_off = r_off.export_artifact(a_posthoc)
    assert r_on.artifact_path == a_stream

    # identical panel BYTES on disk, identical scales, CRCs, assembly
    for fname in ("mean_q8.bin", "sd_q8.bin"):
        with open(os.path.join(a_stream, fname), "rb") as f:
            b_stream = f.read()
        with open(os.path.join(a_posthoc, fname), "rb") as f:
            b_posthoc = f.read()
        assert b_stream == b_posthoc, f"{fname} bytes differ"
    from dcfm_tpu.serve.artifact import PosteriorArtifact
    art_on = PosteriorArtifact.open(a_stream)
    np.testing.assert_array_equal(art_on.mean_scale, art_off.mean_scale)
    np.testing.assert_array_equal(art_on.sd_scale, art_off.sd_scale)
    assert art_on.meta["panel_crc"] == art_off.meta["panel_crc"]
    np.testing.assert_array_equal(art_on.assemble(), art_off.assemble())
    np.testing.assert_array_equal(art_on.assemble(kind="sd"),
                                  art_off.assemble(kind="sd"))


def test_stream_artifact_export_is_free(data, tmp_path):
    """export_artifact to the streamed path must OPEN, not rewrite: the
    panel file's mtime is untouched."""
    a = str(tmp_path / "art")
    res = fit(data, _cfg("auto", stream_artifact=a))
    panel = os.path.join(a, "mean_q8.bin")
    before = os.stat(panel).st_mtime_ns
    art = res.export_artifact(a)
    assert os.stat(panel).st_mtime_ns == before
    assert art.g == 8
    # a DIFFERENT path still exports the classic way
    art2 = res.export_artifact(str(tmp_path / "other"))
    np.testing.assert_array_equal(np.asarray(art.mean_panels),
                                  np.asarray(art2.mean_panels))


def test_stream_artifact_result_survives_re_stream(data, tmp_path):
    """The FitResult must not alias the artifact's WRITABLE landing
    memmaps: its panels are rebound to the finalized artifact's
    read-only maps (mutation cannot corrupt the CRC'd bytes), and a
    second stream to the same path creates a fresh inode, so the first
    result's lazy panel views keep the first posterior's bytes."""
    a = str(tmp_path / "art")
    r1 = fit(data, _cfg("auto", stream_artifact=a))
    assert r1._q8_panels is not None
    assert not r1._q8_panels.flags.writeable
    panels_before = np.array(r1._q8_panels, copy=True)
    sigma_before = np.array(r1.Sigma, copy=True)
    # different data -> different posterior bytes land at the SAME path
    Y2, _ = make_synthetic(n=60, p=96, k_true=3, seed=1)
    r2 = fit(Y2, _cfg("auto", stream_artifact=a))
    assert not np.array_equal(np.asarray(r2._q8_panels), panels_before)
    np.testing.assert_array_equal(np.asarray(r1._q8_panels), panels_before)
    np.testing.assert_array_equal(r1.Sigma, sigma_before)
    with pytest.raises(ValueError):
        r1._q8_panels[0, 0, 0] = 0


def test_interrupted_stream_artifact_refuses_to_open(data, tmp_path):
    """A crash mid-stream leaves panel bytes but no meta.json: the
    artifact must refuse cleanly (meta is invalidated at stream begin,
    written only at finalize)."""
    from dcfm_tpu.serve.artifact import (
        ArtifactError, PosteriorArtifact, begin_streamed_artifact)
    a = str(tmp_path / "torn")
    res = fit(data, _cfg("auto", stream_artifact=a))
    assert res.artifact_path == a
    # simulate the next fit crashing mid-stream: begin invalidates meta
    begin_streamed_artifact(a, g=8, P=12, has_sd=False)
    with pytest.raises(ArtifactError, match="no meta.json"):
        PosteriorArtifact.open(a)


# ---------------------------------------------------------------------------
# ownership: the drain commits owned copies (PR-1/PR-5 UAF class)
# ---------------------------------------------------------------------------

def test_drain_commits_owned_copies_sources_deleted():
    """Pin the owned-copy discipline: delete the device-side source
    right after submit; the landed panels must be unperturbed, and the
    landing buffers must OWN their memory (no aliasing of any jax
    buffer that a later delete()/donation could invalidate)."""
    import jax.numpy as jnp

    from dcfm_tpu.models.state import num_padded_pairs, num_upper_pairs
    from dcfm_tpu.runtime.fetch import fetch_jit
    from dcfm_tpu.runtime.pipeline import StreamingFetcher
    from dcfm_tpu.serve.artifact import quantize_panels

    g, P = 3, 4
    rng = np.random.default_rng(3)
    acc_host = rng.standard_normal(
        (num_padded_pairs(g), P, P)).astype(np.float32)
    n_pairs = num_upper_pairs(g)
    # host-side twin quantizer: bitwise the device fetch (the pinned
    # serve/artifact contract), so the expectation is source-independent
    expect_q, expect_s = quantize_panels(acc_host[:n_pairs])

    acc = jnp.asarray(acc_host)
    sf = StreamingFetcher(
        fetch_jit(g, 1, "quant8"),
        lambda a0: (np.float32(1.0), np.float32(1.0)),
        (n_pairs, P, P), 0)
    assert sf.submit(acc, final=True)
    acc.delete()                      # source dies while the drain runs
    res = sf.finish()
    assert res["final_landed"]
    np.testing.assert_array_equal(res["q8"], expect_q)
    np.testing.assert_array_equal(res["scales"], expect_s)
    # owned host memory: no view into anything jax can free
    assert res["q8"].flags.owndata and res["q8"].base is None
    assert res["scales"].flags.owndata and res["scales"].base is None


def test_streamer_abort_joins_worker():
    """abort() must stop the background worker even with nothing queued
    (a blocked non-daemon drain would hang interpreter shutdown)."""
    from dcfm_tpu.runtime.fetch import fetch_jit
    from dcfm_tpu.runtime.pipeline import StreamingFetcher

    sf = StreamingFetcher(
        fetch_jit(3, 1, "quant8"),
        lambda a0: (np.float32(1.0), np.float32(1.0)), (6, 4, 4), 0)
    sf.abort()
    assert not sf._worker.is_alive()
    sf.abort()                        # idempotent


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def test_validate_rejects_bad_stream_config():
    base = _cfg()
    bad = FitConfig(model=base.model, run=base.run,
                    backend=BackendConfig(fetch_dtype="float32",
                                          fetch_stream="on"))
    with pytest.raises(ValueError, match="fetch_stream"):
        validate(bad, 60, 96)
    bad2 = FitConfig(model=base.model, run=base.run,
                     backend=BackendConfig(fetch_dtype="float32"),
                     stream_artifact="/tmp/x")
    with pytest.raises(ValueError, match="stream_artifact"):
        validate(bad2, 60, 96)
    bad3 = FitConfig(model=base.model, run=base.run,
                     backend=BackendConfig(fetch_dtype="quant8",
                                           fetch_stream="nope"))
    with pytest.raises(ValueError, match="fetch_stream"):
        validate(bad3, 60, 96)
    bad4 = FitConfig(model=base.model, run=base.run,
                     backend=BackendConfig(fetch_dtype="quant8",
                                           fetch_stream="off"),
                     stream_artifact="/tmp/x")
    with pytest.raises(ValueError, match="stream_artifact"):
        validate(bad4, 60, 96)


# ---------------------------------------------------------------------------
# mid-stream SIGKILL + supervised resume (the PR 4/5 fault seams)
# ---------------------------------------------------------------------------

def test_midstream_sigkill_supervised_resume_bit_exact(tmp_path,
                                                       monkeypatch):
    """A kill_event lands INSIDE the streaming window (the new
    ``stream_submit`` seam fires at the chunk boundary right as the
    snapshot is dispatched); the supervisor relaunches, the resumed
    child re-streams, and the final Sigma is BIT-IDENTICAL to an
    uninterrupted streamed run."""
    from dcfm_tpu.resilience import supervise

    Y, _ = make_synthetic(n=40, p=24, k_true=3, seed=7)
    small = dict(model=ModelConfig(num_shards=2, factors_per_shard=3,
                                   rho=0.8),
                 run=RunConfig(burnin=16, mcmc=16, thin=2, seed=3,
                               chunk_size=8),
                 backend=BackendConfig(fetch_dtype="quant8",
                                       fetch_stream="auto"))
    ref = fit(Y, FitConfig(**small))
    assert ref.stream_stats is not None          # the reference streamed

    ck = str(tmp_path / "stream.ck.npz")
    cfg = FitConfig(**small, checkpoint_path=ck,
                    checkpoint_every_chunks=1, checkpoint_keep_last=2)
    # children inherit the env plan; the shared compile cache keeps the
    # relaunches cheap
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(REPO, ".jax_cache"))
    monkeypatch.setenv(faults.ENV_VAR, json.dumps({"faults": [
        {"op": "kill_event", "event": "stream_submit",
         "at_occurrence": 1, "at_launch": 1}]}))
    # the PARENT must not execute the plan (its no-op resume would die
    # at its own stream seam): neutralize it in-process
    faults.install({"faults": []})
    res = supervise(Y, cfg, backoff_base=0.05)
    assert res.supervise_report.launches == 2
    assert res.supervise_report.deaths[0][0] == -9   # a real SIGKILL
    np.testing.assert_array_equal(res.Sigma, ref.Sigma)
    np.testing.assert_array_equal(res.sigma_blocks, ref.sigma_blocks)
