"""Posterior artifact (dcfm_tpu/serve/artifact.py): export round-trips.

Pins the durability layer of the serving subsystem: export -> open is
bitwise for both panel sets, a checkpoint-sourced export matches a
FitResult-sourced one with no refit, a version mismatch refuses with a
clear error instead of crashing, and a p=50k-scale artifact opens via
memmap without materializing anything dense (the panel files are
filesystem holes - kilobytes of real disk for a 1.3 GB logical
artifact).
"""

import json
import os

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.serve.artifact import (
    ArtifactError, ArtifactVersionError, PosteriorArtifact,
    create_sparse_artifact, export_fit_result, export_from_checkpoint,
    quantize_panels, write_artifact)


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    """One checkpointed posterior-SD fit shared by the module (the chain
    is the slow part; every test here exercises the export layer)."""
    Y, _ = make_synthetic(n=50, p=25, k_true=3, seed=5)
    Y[:, 7] = 0.0               # exercise the zero-column path
    td = tmp_path_factory.mktemp("serve_artifact")
    ck = str(td / "chain.npz")
    cfg = FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.9,
                          posterior_sd=True),
        run=RunConfig(burnin=30, mcmc=30, thin=2, seed=0, chunk_size=15),
        backend=BackendConfig(fetch_dtype="quant8"),
        checkpoint_path=ck)
    return fit(Y, cfg), Y, ck, td


def test_export_open_roundtrip_bitwise(fitted, tmp_path):
    res, _, _, _ = fitted
    art = export_fit_result(res, str(tmp_path / "art"))
    # the default quant8 fetch's int8 panels are written as-is
    np.testing.assert_array_equal(np.asarray(art.mean_panels),
                                  np.asarray(res._q8_panels))
    np.testing.assert_array_equal(art.mean_scale,
                                  np.asarray(res._q8_scales))
    np.testing.assert_array_equal(np.asarray(art.sd_panels),
                                  np.asarray(res._sd_q8_panels))
    np.testing.assert_array_equal(art.sd_scale,
                                  np.asarray(res._sd_q8_scales))
    # reopening reads the same bytes back (memmap vs written arrays)
    art2 = PosteriorArtifact.open(art.path)
    np.testing.assert_array_equal(np.asarray(art2.mean_panels),
                                  np.asarray(art.mean_panels))
    np.testing.assert_array_equal(np.asarray(art2.sd_panels),
                                  np.asarray(art.sd_panels))
    # preprocess maps survive the round trip
    np.testing.assert_array_equal(art2.pre.inv_perm, res.preprocess.inv_perm)
    np.testing.assert_array_equal(art2.pre.kept_cols,
                                  res.preprocess.kept_cols)
    np.testing.assert_array_equal(art2.pre.zero_cols,
                                  res.preprocess.zero_cols)
    np.testing.assert_array_equal(art2.pre.col_scale,
                                  res.preprocess.col_scale)


def test_export_quantizes_float_panels_like_the_device(fitted, tmp_path):
    """A float32-fetch FitResult quantizes host-side with the device's
    max-abs rule: same panels as the quant8 fetch of the same chain."""
    res, _, _, _ = fitted
    q, s = quantize_panels(res.upper_panels)
    np.testing.assert_array_equal(q, np.asarray(res._q8_panels))
    np.testing.assert_array_equal(s, np.asarray(res._q8_scales))


def test_checkpoint_export_matches_fitresult_export(fitted, tmp_path):
    """No-refit export from the v6 checkpoint: MEAN panels and scales are
    bitwise the FitResult-sourced export's; SD panels agree to within one
    int8 quantization step (the device fuses m2 - mean^2 into an FMA the
    host replay cannot reproduce exactly - documented in
    export_from_checkpoint)."""
    res, Y, ck, _ = fitted
    a1 = export_fit_result(res, str(tmp_path / "a_fit"))
    a2 = export_from_checkpoint(ck, Y, str(tmp_path / "a_ck"))
    np.testing.assert_array_equal(np.asarray(a1.mean_panels),
                                  np.asarray(a2.mean_panels))
    np.testing.assert_array_equal(a1.mean_scale, a2.mean_scale)
    np.testing.assert_array_equal(a1.pre.inv_perm, a2.pre.inv_perm)
    np.testing.assert_array_equal(a1.pre.col_scale, a2.pre.col_scale)
    np.testing.assert_allclose(a1.sd_scale, a2.sd_scale, rtol=1e-5)
    from dcfm_tpu.utils.estimate import dequantize_panels
    d1 = dequantize_panels(np.ascontiguousarray(a1.sd_panels), a1.sd_scale)
    d2 = dequantize_panels(np.ascontiguousarray(a2.sd_panels), a2.sd_scale)
    step = np.maximum(a1.sd_scale, a2.sd_scale) / 127.0
    assert (np.abs(d1 - d2) <= step[:, None, None] * 1.001).all()


def test_checkpoint_export_refuses_wrong_data(fitted, tmp_path):
    res, Y, ck, _ = fitted
    with pytest.raises(ArtifactError, match="fingerprint"):
        export_from_checkpoint(ck, Y + 1.0, str(tmp_path / "bad"))


def test_version_mismatch_is_a_clear_error(fitted, tmp_path):
    res, _, _, _ = fitted
    art = export_fit_result(res, str(tmp_path / "art"))
    meta_path = os.path.join(art.path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["version"] = 99
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ArtifactVersionError, match="v99"):
        PosteriorArtifact.open(art.path)
    # not-an-artifact directory is equally clear
    with pytest.raises(ArtifactError, match="meta.json"):
        PosteriorArtifact.open(str(tmp_path))


def test_truncated_panel_file_refuses(fitted, tmp_path):
    res, _, _, _ = fitted
    art = export_fit_result(res, str(tmp_path / "art"))
    panels = os.path.join(art.path, "mean_q8.bin")
    with open(panels, "r+b") as f:
        f.truncate(os.path.getsize(panels) - 1)
    with pytest.raises(ArtifactError, match="bytes"):
        PosteriorArtifact.open(art.path)


def test_offline_assembly_matches_fit_sigma(fitted, tmp_path):
    """The artifact's offline assembly reproduces FitResult.Sigma exactly
    when the native assembler computed both (same kernel, same panels);
    the engine tests pin served == offline on top of this."""
    res, _, _, _ = fitted
    from dcfm_tpu import native
    art = export_fit_result(res, str(tmp_path / "art"))
    got = art.assemble()
    if native.available():
        np.testing.assert_array_equal(got, res.Sigma)
    else:
        np.testing.assert_allclose(got, res.Sigma, rtol=1e-5, atol=1e-7)


def test_p50k_scale_artifact_opens_sparse(tmp_path):
    """A p=50,000 artifact (g=100, P=500: 1.26 GB of logical panels)
    opens via memmap in well under a second, costs ~nothing on disk
    (filesystem holes), and serves entries without touching the dense
    Sigma - only the pages a query lands on are ever read."""
    import time
    path = create_sparse_artifact(str(tmp_path / "big"), g=100, P=500)
    logical = 100 * 101 // 2 * 500 * 500
    st = os.stat(os.path.join(path, "mean_q8.bin"))
    assert st.st_size == logical
    assert st.st_blocks * 512 < logical // 100     # hole-backed
    t0 = time.perf_counter()
    art = PosteriorArtifact.open(path)
    assert time.perf_counter() - t0 < 1.0
    assert art.p_original == 50_000
    assert isinstance(art.mean_panels, np.memmap)
    # patch one panel's bytes through a writable view and read it back
    # through the artifact: pair (0, 1) holds rows of shard 0 vs shard 1
    mm = np.memmap(os.path.join(path, "mean_q8.bin"), dtype=np.int8,
                   mode="r+", shape=(art.n_pairs, art.P, art.P))
    mm[1, 3, 4] = 42
    mm.flush()
    del mm
    from dcfm_tpu.serve.engine import QueryEngine
    eng = QueryEngine(art, cache_bytes=8 << 20)
    # caller (3, 500 + 4): shard 0 local 3 x shard 1 local 4 -> panel 1
    v = eng.entry(3, 504, destandardize=False)
    assert v == np.float32(42.0 / 127.0)
    assert eng.entry(504, 3, destandardize=False) == v   # symmetry
    assert eng.entry(0, 0) == np.float32(0.0)            # untouched hole


def test_reexport_over_existing_artifact(fitted, tmp_path):
    """Re-exporting into the same directory stays atomic-by-refusal: the
    old meta is dropped before any payload write (a crash mid-re-export
    must not leave new panels validated by stale metadata), and stale SD
    panels from a previous has_sd export do not linger."""
    res, _, _, _ = fitted
    path = str(tmp_path / "art")
    export_fit_result(res, path)                     # has_sd=True
    art = write_artifact(path,                       # re-export, no SD
                         mean_q8=np.asarray(res._q8_panels),
                         mean_scale=np.asarray(res._q8_scales),
                         pre=res.preprocess)
    assert art.has_sd is False
    assert not os.path.exists(os.path.join(path, "sd_q8.bin"))
    reopened = PosteriorArtifact.open(path)
    np.testing.assert_array_equal(np.asarray(reopened.mean_panels),
                                  np.asarray(res._q8_panels))


def test_write_artifact_validates_shapes(fitted, tmp_path):
    res, _, _, _ = fitted
    pre = res.preprocess
    q = np.asarray(res._q8_panels)
    s = np.asarray(res._q8_scales)
    with pytest.raises(ValueError, match="upper-triangle"):
        write_artifact(str(tmp_path / "bad"), mean_q8=q[:-1],
                       mean_scale=s[:-1], pre=pre)
    with pytest.raises(ValueError, match="together"):
        write_artifact(str(tmp_path / "bad2"), mean_q8=q, mean_scale=s,
                       pre=pre, sd_q8=q)
