"""Delta artifact promotion: format, byte-identity, refusal, chaos.

Pins ``serve/delta.py`` end to end:

* the byte-identity contract: ``materialize_delta(base,
  write_delta_artifact(cand, base))`` reconstructs panel binaries,
  ``maps.npz`` AND ``meta.json`` byte-for-byte equal to the candidate
  (the per-panel CRC tables prove it, the verbatim meta copy lands it);
* an empty delta (idempotent re-promotion) and a maps-only change both
  roundtrip byte-identically with zero panel bytes shipped;
* a single bit-flip anywhere in the delta payload refuses at
  materialize time with the typed ArtifactCorruptError, the promotion
  pointer unmoved and the old generation still serving its exact bytes;
* a delta applied to the wrong base refuses with the typed
  DeltaBaseMismatchError (the full-artifact fallback cue);
* SIGKILL mid-materialization (the ``delta_materialize`` kill point)
  leaves the pointer and serving generation untouched and a torn
  unopenable target; a clean retry promotes - crash-only, like every
  write path upstream;
* memmap adoption across a hot-swap: unchanged pairs serve from the
  PREDECESSOR generation's memmaps (object identity, not a re-open),
  the stricter scale-aware predicate refuses scale-only "unchanged"
  panels, and the pre-warmer carries the hot set over bitwise;
* ``dcfm-tpu delta`` / ``dcfm-tpu promote --delta`` operator paths and
  the flight-recorder trail ``dcfm-tpu events`` summarizes.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from dcfm_tpu.obs.cli import summarize
from dcfm_tpu.obs.recorder import FlightRecorder, install, uninstall
from dcfm_tpu.serve.artifact import (
    ArtifactCorruptError, ArtifactError, MAPS_FILE, MEAN_PANELS_FILE,
    META_FILE, SD_PANELS_FILE, PosteriorArtifact, artifact_fingerprint,
    panel_crc32, write_artifact)
from dcfm_tpu.serve.delta import (
    CANDIDATE_META_FILE, DELTA_META_FILE, MEAN_DELTA_FILE, DeltaArtifact,
    DeltaBaseMismatchError, DeltaError, changed_pairs, materialize_delta,
    write_delta_artifact)
from dcfm_tpu.serve.engine import QueryEngine
from dcfm_tpu.serve.promote import (promote_artifact, promote_delta,
                                    read_pointer)
from dcfm_tpu.serve.server import GENERATION_HEADER, PosteriorServer
from dcfm_tpu.utils.preprocess import preprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

G = 3                       # 6 canonical pairs: diag pairs are 0, 3, 5
P_ORIG = 24


def _make_artifact(path, *, seed=0, p=P_ORIG, g=G):
    """Small CRC'd artifact with random panels - no fit, no jax (the
    serve plane's own test idiom, see test_serve_fleet)."""
    rng = np.random.default_rng(seed)
    Y = rng.standard_normal((40, p)).astype(np.float32)
    pre = preprocess(Y, g)
    n_pairs = g * (g + 1) // 2
    P = pre.shard_size
    q = rng.integers(-127, 128, size=(n_pairs, P, P)).astype(np.int8)
    pair = 0
    for a in range(g):
        for b in range(a, g):
            if a == b:
                q[pair] = np.triu(q[pair]) + np.triu(q[pair], 1).T
            pair += 1
    return write_artifact(
        path, mean_q8=q, pre=pre,
        mean_scale=rng.uniform(0.5, 1.5, n_pairs).astype(np.float32),
        sd_q8=rng.integers(1, 128, size=(n_pairs, P, P)).astype(np.int8),
        sd_scale=rng.uniform(0.5, 1.5, n_pairs).astype(np.float32)).path


def _partial_variant(src, dst, *, mean_pairs=(), sd_pairs=()):
    """Copy ``src`` and XOR-perturb exactly the named pairs' panels
    (symmetry-preserving, so diagonal pairs stay legal), re-recording
    CRCs + fingerprint - a candidate whose change is honestly
    localized, which a relineaged warm refit never is."""
    shutil.copytree(src, dst)
    with open(os.path.join(dst, META_FILE), "r", encoding="utf-8") as f:
        meta = json.load(f)
    n_pairs = meta["g"] * (meta["g"] + 1) // 2
    P = meta["P"]
    for fname, kind, pairs in ((MEAN_PANELS_FILE, "mean", mean_pairs),
                               (SD_PANELS_FILE, "sd", sd_pairs)):
        if not pairs:
            continue
        q = np.memmap(os.path.join(dst, fname), dtype=np.int8,
                      mode="r+", shape=(n_pairs, P, P))
        for pair in pairs:
            q[pair] ^= 0x55
        q.flush()
        meta["panel_crc"][kind] = [int(panel_crc32(np.asarray(pnl)))
                                   for pnl in q]
    meta["fingerprint"] = artifact_fingerprint(meta)
    with open(os.path.join(dst, META_FILE), "w", encoding="utf-8") as f:
        json.dump(meta, f)
    return dst


def _flip_byte(path, offset=7):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0x5A]))


def _read(path):
    with open(path, "rb") as f:
        return f.read()


def _assert_byte_identical(out, cand):
    for name in (MEAN_PANELS_FILE, SD_PANELS_FILE, MAPS_FILE, META_FILE):
        assert _read(os.path.join(out, name)) == \
            _read(os.path.join(cand, name)), name


# ---------------------------------------------------------------------------
# format + byte identity
# ---------------------------------------------------------------------------

def test_roundtrip_byte_identical(tmp_path):
    """THE contract: materialize(base, delta(cand, base)) == cand, byte
    for byte, with only the changed panels' bytes in the delta."""
    v1 = _make_artifact(str(tmp_path / "v1"), seed=1)
    cand = _partial_variant(v1, str(tmp_path / "cand"),
                            mean_pairs=(1, 4), sd_pairs=(2,))
    base = PosteriorArtifact.open(v1)
    d = write_delta_artifact(cand, base, str(tmp_path / "delta"))
    assert [int(i) for i in d.changed["mean"]] == [1, 4]
    assert [int(i) for i in d.changed["sd"]] == [2]
    assert d.panels_changed == 3
    # the packed payload is exactly the changed panels
    P = base.P
    assert os.path.getsize(os.path.join(d.path, MEAN_DELTA_FILE)) \
        == 2 * P * P
    assert d.bytes_shipped < d.full_bytes
    art = materialize_delta(base, d, str(tmp_path / "out"))
    _assert_byte_identical(str(tmp_path / "out"), cand)
    assert art.fingerprint == PosteriorArtifact.open(cand).fingerprint
    # the reconstruction serves: full CRC sweep passes
    for kind in ("mean", "sd"):
        for pair in range(art.n_pairs):
            art.verify_panel(kind, pair)


def test_changed_pairs_is_the_crc_diff(tmp_path):
    v1 = _make_artifact(str(tmp_path / "v1"), seed=2)
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(0, 5))
    got = changed_pairs(PosteriorArtifact.open(v1),
                        PosteriorArtifact.open(cand))
    assert [int(i) for i in got["mean"]] == [0, 5]
    assert list(got["sd"]) == []


def test_empty_delta_roundtrips(tmp_path):
    """Identical candidate -> zero panels shipped, no packed file, and
    materialization still lands a byte-identical artifact (idempotent
    re-promotion ships O(meta), not O(p^2))."""
    v1 = _make_artifact(str(tmp_path / "v1"), seed=3)
    cand = str(tmp_path / "cand")
    shutil.copytree(v1, cand)
    d = write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))
    assert d.panels_changed == 0
    assert not os.path.exists(os.path.join(d.path, MEAN_DELTA_FILE))
    materialize_delta(v1, d, str(tmp_path / "out"))
    _assert_byte_identical(str(tmp_path / "out"), cand)


def test_shape_mismatch_is_a_fallback_cue(tmp_path):
    v1 = _make_artifact(str(tmp_path / "v1"), seed=4, g=2)
    v2 = _make_artifact(str(tmp_path / "v2"), seed=4, g=3)
    with pytest.raises(DeltaError, match="ship the full artifact"):
        write_delta_artifact(v2, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))


def test_missing_crc_table_is_a_fallback_cue(tmp_path):
    v1 = _make_artifact(str(tmp_path / "v1"), seed=5)
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(1,))
    mp = os.path.join(v1, META_FILE)
    with open(mp, "r", encoding="utf-8") as f:
        meta = json.load(f)
    del meta["panel_crc"]
    with open(mp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    with pytest.raises(DeltaError, match="CRC"):
        write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))


def test_torn_delta_refuses_to_open(tmp_path):
    """delta.json is written last: a crash mid-export leaves a directory
    DeltaArtifact.open refuses (the meta-last discipline)."""
    v1 = _make_artifact(str(tmp_path / "v1"), seed=6)
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(1,))
    d = write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))
    os.unlink(os.path.join(d.path, DELTA_META_FILE))
    with pytest.raises(DeltaError, match="not a delta artifact"):
        DeltaArtifact.open(d.path)


# ---------------------------------------------------------------------------
# refusal: corruption and wrong base
# ---------------------------------------------------------------------------

def test_bit_flip_refuses_and_old_generation_keeps_serving(tmp_path):
    """Acceptance: one flipped bit in a delta panel refuses at
    materialize with the pointer unmoved and generation 1 still
    serving its exact bytes."""
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=7)
    promote_artifact(root, "v1")
    ref = PosteriorArtifact.open(v1).assemble()
    cand = _partial_variant(v1, str(tmp_path / "cand"),
                            mean_pairs=(1,), sd_pairs=(4,))
    write_delta_artifact(cand, PosteriorArtifact.open(v1),
                         os.path.join(root, "v2.delta"))
    _flip_byte(os.path.join(root, "v2.delta", MEAN_DELTA_FILE))
    srv = PosteriorServer(root, port=0, swap_poll=0.0)
    srv.start()
    try:
        with pytest.raises(ArtifactCorruptError, match="fails its CRC32"):
            promote_delta(root, "v2.delta", candidate="v2")
        # pointer never moved, the target was never made openable
        st = read_pointer(root)
        assert (st.generation, st.target) == (1, "v1")
        assert not os.path.exists(
            os.path.join(root, "v2", META_FILE))
        status, body, hdr = srv.handle("/v1/entry",
                                       {"i": ["0"], "j": ["1"]})
        assert status == 200 and hdr[GENERATION_HEADER] == "1"
        assert np.float32(body["value"]) == np.float32(ref[0, 1])
    finally:
        srv.close()


def test_wrong_base_refuses_with_the_typed_mismatch(tmp_path):
    v1 = _make_artifact(str(tmp_path / "v1"), seed=8)
    other = _make_artifact(str(tmp_path / "other"), seed=99)
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(2,))
    d = write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))
    with pytest.raises(DeltaBaseMismatchError,
                       match="pull the full candidate"):
        materialize_delta(other, d, str(tmp_path / "out"))
    assert not os.path.exists(str(tmp_path / "out"))


def test_rotted_base_panel_refuses_before_meta_lands(tmp_path):
    """An unchanged panel whose BASE bytes rotted on disk fails the
    materialize-time sweep against the candidate's CRC table - the
    output stays unopenable."""
    v1 = _make_artifact(str(tmp_path / "v1"), seed=9)
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(1,))
    d = write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             str(tmp_path / "delta"))
    # rot an UNCHANGED panel region of the base (pair 0 starts at 0)
    _flip_byte(os.path.join(v1, MEAN_PANELS_FILE), offset=3)
    out = str(tmp_path / "out")
    with pytest.raises(ArtifactCorruptError, match="stays unopenable"):
        materialize_delta(v1, d, out)
    with pytest.raises(ArtifactError):
        PosteriorArtifact.open(out)


# ---------------------------------------------------------------------------
# chaos: SIGKILL mid-materialization (the promote --delta operator path)
# ---------------------------------------------------------------------------

def test_sigkill_mid_materialize_keeps_serving_then_clean_retry(tmp_path):
    """Acceptance chaos point: a SIGKILL at the ``delta_materialize``
    seam (panel bytes landed, meta not yet written) leaves the pointer
    and serving generation untouched and the target unopenable; the
    SAME promote command retried without the fault completes."""
    # the candidate is STAGED outside the promotion root (the online
    # loop's layout): the delta names it "v2", so promote --delta
    # materializes root/v2 rather than adopting the staging dir
    root = str(tmp_path / "root")
    os.makedirs(root)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=10)
    promote_artifact(root, "v1")
    cand = str(tmp_path / "v2")
    _partial_variant(v1, cand, mean_pairs=(0, 3), sd_pairs=(5,))
    write_delta_artifact(cand, PosteriorArtifact.open(v1),
                         os.path.join(root, "v2.delta"))
    cmd = [sys.executable, "-m", "dcfm_tpu.cli", "promote", root,
           "v2.delta", "--delta"]
    env = dict(os.environ)
    env["DCFM_FAULT_PLAN"] = json.dumps({"faults": [
        {"op": "kill_event", "event": "delta_materialize",
         "at_occurrence": 1}]})
    cp = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        env=env, timeout=120)
    assert cp.returncode == -9, (cp.returncode, cp.stderr)
    st = read_pointer(root)
    assert (st.generation, st.target) == (1, "v1")
    # the torn materialization is unopenable (panel bytes, no meta)
    assert os.path.exists(os.path.join(root, "v2", MEAN_PANELS_FILE))
    with pytest.raises(ArtifactError):
        PosteriorArtifact.open(os.path.join(root, "v2"))
    # clean retry: same command, no fault plan
    env.pop("DCFM_FAULT_PLAN")
    cp = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        env=env, timeout=120)
    assert cp.returncode == 0, cp.stderr
    out = json.loads(cp.stdout)
    assert out["generation"] == 2 and out["delta"] is True
    assert out["panels_changed"] == 3
    st = read_pointer(root)
    assert (st.generation, st.target) == (2, "v2")
    _assert_byte_identical(os.path.join(root, "v2"), cand)


def test_promote_delta_is_idempotent(tmp_path):
    """Re-promoting the same delta adopts the already-materialized
    byte-identical target instead of rebuilding it, and the generation
    still only moves forward."""
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=11)
    promote_artifact(root, "v1")
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(2,))
    write_delta_artifact(cand, PosteriorArtifact.open(v1),
                         os.path.join(root, "v2.delta"))
    st = promote_delta(root, "v2.delta", candidate="v2")
    assert st.generation == 2
    # second promotion of the same delta: pointer moves to gen 3 (the
    # CAS is monotonic) but the target bytes are adopted, not rebuilt
    ino = os.stat(os.path.join(root, "v2", MEAN_PANELS_FILE)).st_ino
    st = promote_delta(root, "v2.delta", candidate="v2")
    assert st.generation == 3
    assert os.stat(
        os.path.join(root, "v2", MEAN_PANELS_FILE)).st_ino == ino


# ---------------------------------------------------------------------------
# memmap adoption + hot-set pre-warm (the re-warm ∝ changed∩hot claim)
# ---------------------------------------------------------------------------

def test_engine_adopts_unchanged_pairs_from_predecessor(tmp_path):
    v1 = _make_artifact(str(tmp_path / "v1"), seed=12)
    cand = _partial_variant(v1, str(tmp_path / "cand"),
                            mean_pairs=(1, 4), sd_pairs=(2,))
    a1 = PosteriorArtifact.open(v1)
    a2 = PosteriorArtifact.open(cand)
    e1 = QueryEngine(a1, cache_bytes=1 << 20)
    # warm a hot set: two pairs that survive, one that changes
    for pair, diag in ((0, True), (2, False), (1, False)):
        e1._panel("mean", pair, diag)
    e2 = QueryEngine(a2, cache_bytes=1 << 20, adopt_from=e1)
    # mean: pairs {0,2,3,5} unchanged; sd: {0,1,3,4,5} unchanged
    assert e2.panels_adopted == 4 + 5
    assert e2.panel_source("mean", 0) == "adopted"
    assert e2.panel_source("mean", 1) == "new"
    assert e2.panel_source("sd", 2) == "new"
    assert e2.panel_source("sd", 4) == "adopted"
    # adopted pairs serve from the PREDECESSOR's memmap OBJECT - not a
    # re-open of the new generation's file
    assert e2._adopted_raw["mean"] is a1.mean_panels
    # the pre-warmer carried exactly the unchanged hot panels (0 and 2;
    # pair 1 changed and must be re-dequantized from the new bytes)
    assert e2.cache_seeded == 2
    # bitwise oracle: every value equals a cold engine on the candidate
    cold = QueryEngine(a2, cache_bytes=1 << 20)
    diag_pairs = {0, 3, 5}
    for kind in ("mean", "sd"):
        for pair in range(a2.n_pairs):
            np.testing.assert_array_equal(
                e2._panel(kind, pair, pair in diag_pairs),
                cold._panel(kind, pair, pair in diag_pairs))


def test_scale_only_change_defeats_adoption_but_not_shipping(tmp_path):
    """The two predicates differ on purpose: a scale-only change ships
    ZERO panel bytes (maps travel verbatim) yet the engine must NOT
    adopt the pair - identical bytes times a different scale is a
    different served value."""
    v1 = _make_artifact(str(tmp_path / "v1"), seed=13)
    cand = str(tmp_path / "cand")
    shutil.copytree(v1, cand)
    mp = os.path.join(cand, MAPS_FILE)
    maps = dict(np.load(mp))
    maps["mean_scale"] = (maps["mean_scale"]
                          * np.float32(2.0)).astype(np.float32)
    np.savez(mp, **maps)
    a1, a2 = PosteriorArtifact.open(v1), PosteriorArtifact.open(cand)
    d = write_delta_artifact(a2, a1, str(tmp_path / "delta"))
    assert d.panels_changed == 0            # shipping: nothing changed
    materialize_delta(a1, d, str(tmp_path / "out"))
    _assert_byte_identical(str(tmp_path / "out"), cand)
    e1 = QueryEngine(a1, cache_bytes=1 << 20)
    e2 = QueryEngine(a2, cache_bytes=1 << 20, adopt_from=e1)
    # adoption: every mean pair's dequant scale changed -> none adopted
    assert all(e2.panel_source("mean", pair) == "new"
               for pair in range(a2.n_pairs))
    # sd scales are untouched, those pairs still adopt
    assert all(e2.panel_source("sd", pair) == "adopted"
               for pair in range(a2.n_pairs))


# ---------------------------------------------------------------------------
# operator CLI + flight-recorder trail
# ---------------------------------------------------------------------------

def test_cli_delta_export_and_apply_roundtrip(tmp_path):
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=14)
    promote_artifact(root, "v1")
    cand = _partial_variant(v1, str(tmp_path / "cand"), mean_pairs=(3,))
    delta_dir = str(tmp_path / "delta")
    # --base accepts a promotion root: its CURRENT target is the base
    cp = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "delta", cand,
         "--base", root, "--out", delta_dir],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert cp.returncode == 0, cp.stderr
    out = json.loads(cp.stdout)
    assert out["panels_changed"] == 1
    assert out["bytes_shipped"] < out["full_bytes"]
    applied = str(tmp_path / "applied")
    cp = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "delta", delta_dir,
         "--base", root, "--out", applied, "--apply"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert cp.returncode == 0, cp.stderr
    assert json.loads(cp.stdout)["fingerprint"] == \
        PosteriorArtifact.open(cand).fingerprint
    _assert_byte_identical(applied, cand)


def test_events_cli_summarizes_the_delta_trail(tmp_path):
    """Satellite: delta_export / delta_promote land in the recorder and
    ``dcfm-tpu events`` surfaces them beside full promotions."""
    root = str(tmp_path / "root")
    os.makedirs(root)
    obs = str(tmp_path / "obs")
    rec = FlightRecorder(obs, role="test")
    install(rec)
    try:
        v1 = _make_artifact(os.path.join(root, "v1"), seed=15)
        promote_artifact(root, "v1")
        cand = _partial_variant(v1, str(tmp_path / "cand"),
                                mean_pairs=(1,), sd_pairs=(1,))
        write_delta_artifact(cand, PosteriorArtifact.open(v1),
                             os.path.join(root, "v2.delta"))
        promote_delta(root, "v2.delta", candidate="v2", drift=0.125)
    finally:
        uninstall(rec)
        rec.close()
    s = summarize(obs)
    assert len(s["delta_exports"]) == 1
    assert s["delta_exports"][0]["panels_changed"] == 2
    assert len(s["delta_promotions"]) == 1
    dp = s["delta_promotions"][0]
    assert dp["target"] == "v2" and dp["generation"] == 2
    assert dp["panels_changed"] == 2
    assert dp["bytes_shipped"] < dp["full_bytes"]
    assert dp["drift"] == 0.125
    assert s["delta_fallbacks"] == []
    # the human summary names the delta promotion too
    from dcfm_tpu.obs.cli import events_main
    assert events_main([obs]) == 0
