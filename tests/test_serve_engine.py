"""Query engine + microbatcher: bitwise answers, cache, backpressure.

The central pin: every value the engine serves equals, BIT FOR BIT, the
corresponding entry of the offline ``assemble_from_q8``-based assembly
of the same artifact (``PosteriorArtifact.assemble``) - including the
destandardize and zero-column-reinsertion paths - while dequantizing
only the panels each query touches.
"""

import threading
import time

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.serve.artifact import export_fit_result
from dcfm_tpu.serve.batcher import DeadlineExceeded, Overloaded, QueryBatcher
from dcfm_tpu.serve.engine import QueryEngine, _norm_ppf


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """Artifact + offline ground truths, shared across the module."""
    Y, _ = make_synthetic(n=50, p=26, k_true=3, seed=7)
    Y[:, 3] = 0.0                # dropped zero column
    cfg = FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.9,
                          posterior_sd=True),
        run=RunConfig(burnin=30, mcmc=30, thin=2, seed=0),
        backend=BackendConfig(fetch_dtype="quant8"))
    res = fit(Y, cfg)
    td = tmp_path_factory.mktemp("serve_engine")
    art = export_fit_result(res, str(td / "art"))
    refs = {
        (True, "mean"): art.assemble(),
        (False, "mean"): art.assemble(destandardize=False),
        (True, "sd"): art.assemble(kind="sd"),
    }
    return art, refs


@pytest.mark.parametrize("destandardize", [True, False])
def test_entries_bitwise_equal_offline(served, destandardize):
    art, refs = served
    ref = refs[(destandardize, "mean")]
    eng = QueryEngine(art, cache_bytes=4 << 20)
    rng = np.random.default_rng(0)
    for _ in range(400):
        i, j = (int(v) for v in rng.integers(0, art.p_original, 2))
        got = eng.entry(i, j, destandardize=destandardize)
        assert np.float32(got) == np.float32(ref[i, j]), (i, j)


def test_zero_column_entries_are_exactly_zero(served):
    art, refs = served
    eng = QueryEngine(art)
    assert eng.entry(3, 10) == np.float32(0.0)
    assert eng.entry(10, 3) == np.float32(0.0)
    assert eng.entry(3, 3) == np.float32(0.0)
    assert refs[(True, "mean")][3, 10] == 0.0


def test_block_row_and_sd_bitwise_equal_offline(served):
    art, refs = served
    eng = QueryEngine(art)
    rng = np.random.default_rng(1)
    rows = rng.integers(0, art.p_original, 9)
    cols = rng.integers(0, art.p_original, 7)
    np.testing.assert_array_equal(
        eng.block(rows, cols),
        refs[(True, "mean")][np.ix_(rows, cols)].astype(np.float32))
    np.testing.assert_array_equal(
        eng.block(rows, cols, destandardize=False),
        refs[(False, "mean")][np.ix_(rows, cols)].astype(np.float32))
    np.testing.assert_array_equal(
        eng.block(rows, cols, kind="sd"),
        refs[(True, "sd")][np.ix_(rows, cols)].astype(np.float32))
    np.testing.assert_array_equal(
        eng.row(5), refs[(True, "mean")][5].astype(np.float32))


def test_interval_normal_approximation(served):
    art, _ = served
    eng = QueryEngine(art)
    mean, sd, lo, hi = eng.interval(6, 8, alpha=0.05)
    assert lo < mean < hi and sd > 0
    z = (hi - mean) / sd
    assert abs(z - 1.959964) < 1e-5          # z_{0.975}
    # tighter alpha -> wider interval
    _, _, lo2, hi2 = eng.interval(6, 8, alpha=0.01)
    assert lo2 < lo and hi2 > hi


def test_norm_ppf_accuracy():
    # spot values vs known quantiles
    for p, z in [(0.975, 1.959964), (0.995, 2.575829), (0.5, 0.0),
                 (0.025, -1.959964), (1e-6, -4.753424)]:
        assert abs(_norm_ppf(p) - z) < 5e-6
    with pytest.raises(ValueError):
        _norm_ppf(0.0)


def _caller_in_shard(art, shard):
    """A caller column whose shard position lands in ``shard`` (skips
    padding positions): shard position s models caller column
    kept_cols[perm[s]]."""
    p_kept = art.p_used - art.n_pad
    for s in range(shard * art.P, (shard + 1) * art.P):
        if art.pre.perm[s] < p_kept:
            return int(art.pre.kept_cols[art.pre.perm[s]])
    raise AssertionError(f"shard {shard} is all padding?")


def test_panel_cache_budget_hits_misses_evictions(served):
    art, _ = served
    panel_bytes = art.P * art.P * 4
    eng = QueryEngine(art, cache_bytes=2 * panel_bytes)   # 2 panels max
    c0, c1 = _caller_in_shard(art, 0), _caller_in_shard(art, 1)
    eng.entry(c0, c0)                  # panel (0, 0)
    eng.entry(c0, c1)                  # panel (0, 1)
    s0 = eng.stats()
    assert s0["misses"] == 2 and s0["panels"] == 2
    eng.entry(c0, c0)                  # hit
    assert eng.stats()["hits"] == s0["hits"] + 1
    eng.entry(c1, c1)                  # panel (1, 1) -> eviction
    s1 = eng.stats()
    assert s1["evictions"] >= 1
    assert s1["bytes"] <= 2 * panel_bytes


def test_batcher_coalesces_by_panel(served):
    art, refs = served
    ref = refs[(True, "mean")]
    eng = QueryEngine(art)
    b = QueryBatcher(eng, max_queue=128, max_batch=64)
    try:
        rng = np.random.default_rng(2)
        pairs = [tuple(int(v) for v in rng.integers(0, art.p_original, 2))
                 for _ in range(40)]
        results = {}

        def one(i, j):
            results[(i, j)] = b.entry(i, j)

        threads = [threading.Thread(target=one, args=p) for p in pairs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for (i, j), v in results.items():
            assert np.float32(v) == np.float32(ref[i, j])
        st = b.stats()
        assert st["served"] == len(pairs)
        assert st["rejected"] == 0
        assert st["batches"] >= 1
    finally:
        b.close()


class _SlowEngine:
    """Engine shim whose batch compute blocks until released - makes
    queue-full backpressure and deadline expiry deterministic."""

    def __init__(self, engine):
        self._engine = engine
        self.gate = threading.Event()

    def entries(self, queries):
        self.gate.wait(5.0)
        return self._engine.entries(queries)


def test_batcher_backpressure_rejects_when_full(served):
    art, _ = served
    slow = _SlowEngine(QueryEngine(art))
    b = QueryBatcher(slow, max_queue=2, max_batch=1, default_timeout=5.0)
    try:
        # the worker grabs the first request and blocks on the gate; two
        # more fill the bounded queue; the next must be REJECTED, not
        # queued or blocked
        holders = [threading.Thread(target=lambda: _swallow(b))
                   for _ in range(3)]
        for t in holders:
            t.start()
        deadline = time.monotonic() + 5.0
        while b.stats()["queue_depth"] < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        with pytest.raises(Overloaded):
            b.entry(0, 0)
        assert b.stats()["rejected"] == 1
        slow.gate.set()
        for t in holders:
            t.join()
    finally:
        slow.gate.set()
        b.close()


def _swallow(b):
    try:
        b.entry(1, 2)
    except Exception:
        pass


def test_batcher_expires_stale_requests(served):
    art, _ = served
    slow = _SlowEngine(QueryEngine(art))
    b = QueryBatcher(slow, max_queue=8, max_batch=4)
    try:
        t = threading.Thread(target=lambda: _swallow(b))
        t.start()                       # occupies the worker at the gate
        time.sleep(0.05)
        err = []

        def stale():
            try:
                b.entry(2, 3, timeout=0.05)
            except DeadlineExceeded:
                err.append("deadline")

        t2 = threading.Thread(target=stale)
        t2.start()
        time.sleep(0.3)                 # let the deadline lapse queued
        slow.gate.set()
        t.join()
        t2.join()
        assert err == ["deadline"]
        assert b.stats()["expired"] >= 1
    finally:
        slow.gate.set()
        b.close()


# ---- lazy per-panel CRC verification (serve hardening) --------------------

def _corrupt_copy(art, dst):
    """Copy an artifact directory and flip one byte of panel 0 in the
    copy's mean panels."""
    import os
    import shutil

    shutil.copytree(art.path, dst)
    mm = np.memmap(os.path.join(dst, "mean_q8.bin"), dtype=np.int8,
                   mode="r+", shape=(art.n_pairs, art.P, art.P))
    mm[0, 0, 0] ^= 1
    mm.flush()
    del mm
    from dcfm_tpu.serve.artifact import PosteriorArtifact
    return PosteriorArtifact.open(dst)


def _caller_index_in_shard(eng, shard, P):
    """A caller-coordinate column whose shard position lies in ``shard``."""
    for i in range(eng.artifact.p_original):
        si = int(eng.shard_index([i])[0])
        if si >= 0 and si // P == shard:
            return i
    raise AssertionError("no column maps to the shard")


def test_corrupt_panel_raises_typed_on_first_touch(served, tmp_path):
    """A flipped byte in a panel surfaces as the TYPED ArtifactCorruptError
    lazily - on the corrupt panel's first dequant - while queries that
    touch only healthy panels keep serving bitwise-correct answers."""
    from dcfm_tpu.serve.artifact import ArtifactCorruptError

    art, refs = served
    bad = _corrupt_copy(art, str(tmp_path / "corrupt"))
    eng = QueryEngine(bad)
    P = bad.P
    i0 = _caller_index_in_shard(eng, 0, P)     # panel (0, 0) - corrupted
    i1 = _caller_index_in_shard(eng, 1, P)     # panel (1, 1) - healthy
    # healthy panel first: served, and bitwise equal to the offline truth
    assert (eng.entry(i1, i1)
            == np.float32(refs[(True, "mean")][i1, i1]))
    with pytest.raises(ArtifactCorruptError) as ei:
        eng.entry(i0, i0)
    assert ei.value.panel == 0 and ei.value.kind == "mean"
    # the corrupt panel never entered the cache: retrying still raises
    with pytest.raises(ArtifactCorruptError):
        eng.entry(i0, i0)
    # and the healthy panel is still served (now from cache)
    assert (eng.entry(i1, i1)
            == np.float32(refs[(True, "mean")][i1, i1]))


def test_server_maps_corrupt_panel_to_typed_503(served, tmp_path):
    """The HTTP layer returns a typed 503 for a corrupt panel - a JSON
    error naming the panel, never a stack trace - while /healthz and
    healthy-panel queries keep working."""
    from dcfm_tpu.serve.server import PosteriorServer

    art, _ = served
    bad = _corrupt_copy(art, str(tmp_path / "corrupt503"))
    srv = PosteriorServer(bad, port=0)
    srv.start()   # close() joins serve_forever; never close an unstarted one
    try:
        eng = srv.engine
        P = bad.P
        i0 = _caller_index_in_shard(eng, 0, P)
        i1 = _caller_index_in_shard(eng, 1, P)
        status, payload, _ = srv.handle(
            "/v1/entry", {"i": [str(i0)], "j": [str(i0)]})
        assert status == 503
        assert payload["corrupt_panel"] == 0 and payload["kind"] == "mean"
        assert "CRC32" in payload["error"]
        assert "Traceback" not in payload["error"]
        # healthy panels and liveness are unaffected
        status, payload, _ = srv.handle(
            "/v1/entry", {"i": [str(i1)], "j": [str(i1)]})
        assert status == 200
        status, payload, _ = srv.handle("/healthz", {})
        assert status == 200
    finally:
        srv.close()


def test_artifact_without_crcs_serves_unverified(served, tmp_path):
    """Back-compat: an artifact whose meta carries no panel_crc (pre-
    integrity export) opens and serves - verification is skipped, not
    demanded."""
    import json
    import os
    import shutil

    art, refs = served
    dst = str(tmp_path / "nocrc")
    shutil.copytree(art.path, dst)
    mp = os.path.join(dst, "meta.json")
    with open(mp) as f:
        meta = json.load(f)
    meta.pop("panel_crc", None)
    with open(mp, "w") as f:
        json.dump(meta, f)
    from dcfm_tpu.serve.artifact import PosteriorArtifact
    eng = QueryEngine(PosteriorArtifact.open(dst))
    assert eng.entry(5, 7) == np.float32(refs[(True, "mean")][5, 7])


def test_hot_panels_and_prewarm_transfer_cache_heat(served):
    """The hot-set pre-warmer's engine half: touch counts rank panels
    hottest-first, prewarm() replays them into a COLD engine so its
    first queries hit instead of dequantizing, and stale keys from an
    older generation's grid are skipped, not crashed on."""
    art, refs = served
    hot_eng = QueryEngine(art, cache_bytes=4 << 20)
    c0, c1 = _caller_in_shard(art, 0), _caller_in_shard(art, 1)
    # skew the traffic: shard 0's diagonal panel is by far the hottest
    for _ in range(10):
        hot_eng.entry(c0, c0)              # panel ("mean", 0)
    hot_eng.entry(c1, c1)                  # panel ("mean", 2), once
    hot = hot_eng.hot_panels(8)
    assert hot == [("mean", 0), ("mean", 2)]   # hottest first

    cold = QueryEngine(art, cache_bytes=4 << 20)
    warmed = cold.prewarm(hot)
    assert warmed == len(hot)
    s = cold.stats()
    assert s["panels"] == len(hot)          # resident before any query
    misses_after_warm = s["misses"]
    # the prewarmed panel now serves from cache: hits, no new misses,
    # and the value is still the bitwise offline reference
    assert cold.entry(c0, c0) == np.float32(refs[(True, "mean")][c0, c0])
    s2 = cold.stats()
    assert s2["hits"] >= 1 and s2["misses"] == misses_after_warm

    # keys beyond this artifact's grid (older/newer generation) skip
    assert cold.prewarm([("mean", 99), ("nope", 0)]) == 0
