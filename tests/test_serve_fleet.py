"""Serving fleet resilience: hot-swap, shedding, supervision, chaos.

Pins the serve-side resilience layer end to end:

* atomic promotion pointer discipline (``serve/promote.py``): verified
  promotion, generation monotonicity, the hardlinked audit trail, and
  the refusal of corrupt candidates;
* artifact hot-swap under a 64-thread live storm: zero dropped
  requests, the generation header never decreases per client, and the
  VALUES are bitwise-correct for whichever generation answered - the
  old artifact's bytes keep serving mid-swap;
* a corrupt candidate promoted by a buggy promoter (``verify=False``)
  is refused by the serving worker while the old artifact keeps
  serving, then a good candidate swaps in cleanly;
* per-connection io_timeout sheds a slow-loris client instead of
  parking a handler thread;
* the ``--workers N`` fleet: SO_REUSEPORT replicas supervised by the
  parent - a SIGKILLed worker is respawned, traffic keeps flowing,
  SIGTERM drains the whole fleet, and ``dcfm-tpu events`` summarizes
  the run; workers that die on arrival trip poison detection;
* the serve chaos harness: seeded ``serve_fuzz_spec`` points driven
  through a REAL fleet subprocess under the loadgen - every response
  ok or typed, zero dropped, zero generation regressions, and the
  fleet never hangs (``communicate(timeout=...)`` is the watchdog
  bound).  Three representative points run in tier-1; the >=25-point
  sweep is ``slow``-marked.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from dcfm_tpu.obs.cli import summarize
from dcfm_tpu.obs.recorder import FlightRecorder, install, uninstall
from dcfm_tpu.resilience.faults import serve_fuzz_spec
from dcfm_tpu.serve.artifact import (
    ArtifactError, MEAN_PANELS_FILE, META_FILE, PosteriorArtifact,
    artifact_fingerprint, panel_crc32, write_artifact)
from dcfm_tpu.serve.delta import write_delta_artifact
from dcfm_tpu.serve.loadgen import run_load
from dcfm_tpu.serve.promote import (promote_artifact, promote_delta,
                                    read_pointer)
from dcfm_tpu.serve.server import GENERATION_HEADER, PosteriorServer
from dcfm_tpu.utils.preprocess import preprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
P_ORIG = 24


def _make_artifact(path, *, seed=0, p=P_ORIG, g=2):
    """A small CRC'd artifact with random panels - no fit, no jax.
    Diagonal-pair panels are symmetrized (a real posterior's diagonal
    blocks are); everything else is arbitrary bytes, which is exactly
    what the bitwise value checks want."""
    rng = np.random.default_rng(seed)
    Y = rng.standard_normal((40, p)).astype(np.float32)
    pre = preprocess(Y, g)
    n_pairs = g * (g + 1) // 2
    P = pre.shard_size
    q = rng.integers(-127, 128, size=(n_pairs, P, P)).astype(np.int8)
    pair = 0
    for a in range(g):
        for b in range(a, g):
            if a == b:
                q[pair] = np.triu(q[pair]) + np.triu(q[pair], 1).T
            pair += 1
    scale = rng.uniform(0.5, 1.5, n_pairs).astype(np.float32)
    sd_q = rng.integers(1, 128, size=(n_pairs, P, P)).astype(np.int8)
    sd_scale = rng.uniform(0.5, 1.5, n_pairs).astype(np.float32)
    art = write_artifact(path, mean_q8=q, mean_scale=scale, pre=pre,
                         sd_q8=sd_q, sd_scale=sd_scale)
    return art.path


def _variant_artifact(src, dst):
    """Copy ``src`` and NEGATE its int8 mean panels in place, then
    re-record the panel CRCs + fingerprint.  int8 quant values live in
    [-127, 127] and every downstream op (dequant scale, symmetrize,
    destandardize) is sign-preserving IEEE arithmetic, so the variant
    serves EXACTLY the negated float32 of the original - a bitwise
    which-generation-answered oracle."""
    shutil.copytree(src, dst)
    with open(os.path.join(dst, META_FILE), "r", encoding="utf-8") as f:
        meta = json.load(f)
    n_pairs = meta["g"] * (meta["g"] + 1) // 2
    q = np.memmap(os.path.join(dst, MEAN_PANELS_FILE), dtype=np.int8,
                  mode="r+", shape=(n_pairs, meta["P"], meta["P"]))
    np.negative(q, out=q)
    q.flush()
    meta["panel_crc"]["mean"] = [int(panel_crc32(np.asarray(panel)))
                                 for panel in q]
    meta["fingerprint"] = artifact_fingerprint(meta)
    with open(os.path.join(dst, META_FILE), "w", encoding="utf-8") as f:
        json.dump(meta, f)
    return dst


def _flip_byte(path, offset=7):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0x5A]))


def _get(base, path, timeout=15):
    """-> (status, payload, headers) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get_retry(base, path, timeout=15, tries=20):
    """_get with reconnects: a SIGKILLed SO_REUSEPORT worker resets
    in-flight connections; the retry lands on a live replica."""
    for attempt in range(tries):
        try:
            return _get(base, path, timeout=timeout)
        except OSError:
            time.sleep(0.05 * (attempt + 1))
    raise AssertionError(f"no replica ever answered {path}")


# ---------------------------------------------------------------------------
# promotion pointer
# ---------------------------------------------------------------------------

def test_promote_pointer_discipline(tmp_path):
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=1)
    st1 = promote_artifact(root, "v1")
    assert st1.generation == 1 and st1.target == "v1"
    assert read_pointer(root).path == v1
    _variant_artifact(v1, os.path.join(root, "v2"))
    st2 = promote_artifact(root, "v2")
    assert st2.generation == 2
    assert st2.fingerprint != st1.fingerprint
    # the audit trail: every pointer that ever served is linked aside
    assert os.path.exists(os.path.join(root, "CURRENT.gen1"))
    assert os.path.exists(os.path.join(root, "CURRENT.gen2"))
    # a corrupt candidate is refused by the verifying promoter and the
    # pointer does not move
    shutil.copytree(os.path.join(root, "v2"), os.path.join(root, "v3"))
    _flip_byte(os.path.join(root, "v3", MEAN_PANELS_FILE))
    with pytest.raises(ArtifactError):
        promote_artifact(root, "v3")
    assert read_pointer(root).generation == 2
    assert read_pointer(root).target == "v2"
    # the operator's path: `dcfm-tpu promote` verifies then publishes
    cp = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "promote", root,
         os.path.join(root, "v1")],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 0, cp.stderr
    assert json.loads(cp.stdout)["generation"] == 3
    assert read_pointer(root).target == "v1"
    # and it refuses the corrupt candidate with a non-zero exit
    cp = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "promote", root,
         os.path.join(root, "v3")],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode != 0
    assert read_pointer(root).generation == 3


# ---------------------------------------------------------------------------
# hot-swap under live traffic (in-process server, 64-thread storm)
# ---------------------------------------------------------------------------

def test_hot_swap_under_64_thread_storm(tmp_path):
    """The tentpole acceptance: promote a new generation in the middle
    of a 64-thread storm.  Zero dropped requests, zero untyped errors,
    per-client generations never decrease, and every 200 is bitwise
    the artifact its generation header names - old bytes mid-swap, new
    bytes after."""
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=3)
    _variant_artifact(v1, os.path.join(root, "v2"))
    ref = PosteriorArtifact.open(v1).assemble()
    promote_artifact(root, "v1")
    srv = PosteriorServer(root, port=0, max_queue=2048, max_batch=64,
                          request_timeout=60.0, swap_poll=0.0)
    host, port = srv.start()
    seen = {"ok": 0}
    promote_once = threading.Event()

    def expect(kind, path, body, gen):
        # promotion is triggered BY traffic: after 200 responses the
        # new generation lands while >= 1000 requests are still in
        # flight - a guaranteed mid-storm swap, no timing guesswork
        seen["ok"] += 1
        if seen["ok"] == 200 and not promote_once.is_set():
            promote_once.set()
            promote_artifact(root, "v2")
        q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
        i, j = int(q["i"][0]), int(q["j"][0])
        want = (np.float32(ref[i, j]) if gen == 1
                else np.float32(-ref[i, j]))
        got = np.float32(body["value"])
        if got != want:
            return (f"generation {gen} entry ({i},{j}): "
                    f"got {got!r} want {want!r}")
        return None

    try:
        res = run_load(f"http://{host}:{port}", threads=64,
                       requests_per_thread=25, seed=7, p=P_ORIG,
                       retries=2, timeout=60.0, expect=expect,
                       route_mix=(("entry", 1),))
        st, m, _ = _get(f"http://{host}:{port}", "/metrics")
    finally:
        srv.close()
    assert res["dropped"] == 0
    assert res["untyped"] == []
    assert res["value_errors"] == []
    assert res["generation"]["violations"] == 0
    assert res["generation"]["min"] == 1       # old bytes served mid-swap
    assert res["generation"]["max"] == 2       # the swap landed under load
    assert st == 200 and m["swap"]["swaps"] == 1
    assert m["swap"]["refused"] == 0


def _partial_variant_artifact(src, dst, pairs):
    """Copy ``src`` and XOR-perturb exactly ``pairs``' mean panels
    (symmetry-preserving), re-recording CRCs + fingerprint - the
    honestly-localized change a delta promotion exists for."""
    shutil.copytree(src, dst)
    with open(os.path.join(dst, META_FILE), "r", encoding="utf-8") as f:
        meta = json.load(f)
    n_pairs = meta["g"] * (meta["g"] + 1) // 2
    q = np.memmap(os.path.join(dst, MEAN_PANELS_FILE), dtype=np.int8,
                  mode="r+", shape=(n_pairs, meta["P"], meta["P"]))
    for pair in pairs:
        q[pair] ^= 0x55
    q.flush()
    meta["panel_crc"]["mean"] = [int(panel_crc32(np.asarray(panel)))
                                 for panel in q]
    meta["fingerprint"] = artifact_fingerprint(meta)
    with open(os.path.join(dst, META_FILE), "w", encoding="utf-8") as f:
        json.dump(meta, f)
    return dst


def test_hot_swap_to_delta_generation_under_storm(tmp_path):
    """The delta tentpole's storm acceptance: generation 2 arrives as a
    DELTA promoted mid-storm.  Zero drops, every 200 bitwise matches
    the artifact its generation header names, the swap ships only the
    changed panels' bytes (recorder-counted), and the new epoch serves
    unchanged pairs from the OLD epoch's adopted memmaps - not a
    re-open of the new generation's files."""
    root = str(tmp_path / "root")
    os.makedirs(root)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=6)
    # stage the candidate OUTSIDE the root; only its delta lands inside
    stage = _partial_variant_artifact(v1, str(tmp_path / "v2"),
                                      pairs=(0, 2))
    a1 = PosteriorArtifact.open(v1)
    ref = {1: a1.assemble(),
           2: PosteriorArtifact.open(stage).assemble()}
    d = write_delta_artifact(stage, a1, os.path.join(root, "v2.delta"))
    assert d.panels_changed == 2 and list(d.changed["sd"]) == []
    promote_artifact(root, "v1")
    rec = FlightRecorder(str(tmp_path / "obs"), role="storm")
    install(rec)
    srv = PosteriorServer(root, port=0, max_queue=2048, max_batch=64,
                          request_timeout=60.0, swap_poll=0.0)
    host, port = srv.start()
    first_engine = srv._epoch.engine
    seen = {"ok": 0}
    promote_once = threading.Event()

    def expect(kind, path, body, gen):
        seen["ok"] += 1
        if seen["ok"] == 200 and not promote_once.is_set():
            promote_once.set()
            promote_delta(root, "v2.delta")
        q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
        i, j = int(q["i"][0]), int(q["j"][0])
        want = np.float32(ref[gen][i, j])
        got = np.float32(body["value"])
        if got != want:
            return (f"generation {gen} entry ({i},{j}): "
                    f"got {got!r} want {want!r}")
        return None

    try:
        res = run_load(f"http://{host}:{port}", threads=64,
                       requests_per_thread=25, seed=11, p=P_ORIG,
                       retries=2, timeout=60.0, expect=expect,
                       route_mix=(("entry", 1),))
        st, m, _ = _get(f"http://{host}:{port}", "/metrics")
    finally:
        srv.close()
        uninstall(rec)
        rec.close()
    assert res["dropped"] == 0
    assert res["untyped"] == []
    assert res["value_errors"] == []
    assert res["generation"]["violations"] == 0
    assert res["generation"]["min"] == 1
    assert res["generation"]["max"] == 2
    assert st == 200 and m["swap"]["swaps"] == 1
    # adoption: mean pair 1 and all three sd pairs are unchanged and
    # serve from the predecessor epoch's memmap OBJECTS
    eng = srv._epoch.engine
    assert eng.artifact.fingerprint == \
        PosteriorArtifact.open(stage).fingerprint
    assert eng.panels_adopted == 1 + 3
    assert eng.panel_source("mean", 0) == "new"
    assert eng.panel_source("mean", 1) == "adopted"
    assert eng.panel_source("sd", 0) == "adopted"
    assert eng._adopted_raw["mean"] is first_engine.artifact.mean_panels
    # the recorder trail: the delta promotion shipped fewer bytes than
    # a full artifact, and the swap event counted the adoption
    s = summarize(str(tmp_path / "obs"))
    assert len(s["delta_promotions"]) == 1
    dp = s["delta_promotions"][0]
    assert dp["panels_changed"] == 2
    assert dp["bytes_shipped"] < dp["full_bytes"]
    swap_events = []
    with open(rec.path, encoding="utf-8") as f:
        for line in f:
            e = json.loads(line)
            if e.get("event") == "serve_swap":
                swap_events.append(e)
    assert len(swap_events) == 1
    sw = swap_events[0]
    assert sw["panels_adopted"] == 4
    assert sw["panels_changed"] == 2
    # exactly the changed panels' bytes + the always-shipped maps - the
    # four adopted panels' bytes never move
    maps_bytes = os.path.getsize(os.path.join(root, "v2", "maps.npz"))
    assert sw["bytes_shipped"] == 2 * a1.P * a1.P + maps_bytes


def test_corrupt_candidate_refused_old_keeps_serving(tmp_path):
    """A buggy promoter publishes a bit-flipped candidate
    (``verify=False``): the worker refuses the swap with a typed event,
    keeps answering from the old artifact at the old generation, and a
    subsequently promoted GOOD candidate swaps in cleanly."""
    root = str(tmp_path)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=4)
    ref = PosteriorArtifact.open(v1).assemble()
    promote_artifact(root, "v1")
    srv = PosteriorServer(root, port=0, swap_poll=0.0)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        v2 = _variant_artifact(v1, os.path.join(root, "v2"))
        _flip_byte(os.path.join(v2, MEAN_PANELS_FILE))
        promote_artifact(root, "v2", verify=False)     # the buggy promoter
        st, e, hdrs = _get(base, "/v1/entry?i=1&j=2")
        assert st == 200
        assert np.float32(e["value"]) == np.float32(ref[1, 2])
        assert hdrs[GENERATION_HEADER] == "1"          # swap refused
        st, h, _ = _get(base, "/healthz")
        assert h["artifact_generation"] == 1
        assert h["pointer_generation"] == 2            # pointer DID move
        st, m, _ = _get(base, "/metrics")
        assert m["swap"]["refused"] >= 1 and m["swap"]["swaps"] == 0
        # recovery: a good candidate promotes and swaps
        _variant_artifact(v1, os.path.join(root, "v3"))
        promote_artifact(root, "v3")
        st, e, hdrs = _get(base, "/v1/entry?i=1&j=2")
        assert st == 200
        assert np.float32(e["value"]) == np.float32(-ref[1, 2])
        assert hdrs[GENERATION_HEADER] == "3"
        st, m, _ = _get(base, "/metrics")
        assert m["swap"]["swaps"] == 1
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# per-connection io_timeout vs. the slow-loris client
# ---------------------------------------------------------------------------

def test_slow_loris_is_shed_not_parked(tmp_path):
    """A client that sends half a request and squats: the per-connection
    io_timeout closes it (recv sees EOF) while real traffic keeps being
    answered, and close() does not hang on a parked handler thread."""
    art = _make_artifact(str(tmp_path / "a"), seed=5)
    srv = PosteriorServer(art, port=0, io_timeout=0.5)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        loris = socket.create_connection((host, port), timeout=5.0)
        loris.sendall(b"GET /healthz HTTP/1.1\r\nHost: loris\r\n")
        # real traffic flows while the loris squats
        st, h, _ = _get(base, "/healthz")
        assert st == 200 and h["status"] in ("ok", "degraded")
        # the server gives up on the silent socket at io_timeout: EOF
        loris.settimeout(10.0)
        assert loris.recv(1024) == b""
        loris.close()
        st, _, _ = _get(base, "/v1/entry?i=0&j=1")
        assert st == 200
    finally:
        t0 = time.monotonic()
        srv.close()
        assert time.monotonic() - t0 < 10.0, "drain parked on the loris"


# ---------------------------------------------------------------------------
# the --workers N fleet (real CLI subprocesses)
# ---------------------------------------------------------------------------

def _readline_bounded(proc, timeout=90.0):
    out = []
    t = threading.Thread(target=lambda: out.append(proc.stdout.readline()))
    t.start()
    t.join(timeout)
    if t.is_alive():
        proc.kill()
        proc.communicate()
        raise AssertionError("fleet never printed its protocol line")
    return out[0]


def _spawn_fleet(root, run_dir, *, workers=2, extra=(), env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "dcfm_tpu.cli", "serve", root,
         "--workers", str(workers), "--port", "0", "--run-dir", run_dir,
         "--fleet-min-uptime", "0.2", "--fleet-backoff", "0.1",
         "--request-timeout", "30", "--swap-poll", "0.05",
         *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env)
    line = _readline_bounded(proc)
    assert line, proc.stderr.read()
    info = json.loads(line)
    return proc, info


def _terminate_fleet(proc, timeout=90.0):
    """SIGTERM + bounded communicate: the harness's no-hang watchdog."""
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    try:
        return proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise AssertionError("fleet hung past the drain bound")


def test_fleet_kill_respawn_drain_and_events(tmp_path):
    root = str(tmp_path / "root")
    os.makedirs(root)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=6)
    ref = PosteriorArtifact.open(v1).assemble()
    promote_artifact(root, "v1")
    run_dir = str(tmp_path / "obs")
    proc, info = _spawn_fleet(root, run_dir, workers=2)
    try:
        assert info["ready"] is True and info["workers"] == 2
        base = info["serving"]
        st, h, _ = _get_retry(base, "/healthz")
        assert st == 200
        # per-worker liveness + fleet-wide table on ANY replica
        assert h["worker"]["index"] in (0, 1)
        assert h["artifact_generation"] == 1
        fleet = h["fleet"]
        assert len(fleet["workers"]) == 2
        pids = [w["pid"] for w in fleet["workers"] if w["alive"]]
        assert len(pids) == 2
        # SIGKILL one worker: the supervisor must respawn it
        os.kill(pids[0], signal.SIGKILL)
        deadline = time.monotonic() + 30.0
        respawned = False
        while time.monotonic() < deadline and not respawned:
            st, h, _ = _get_retry(base, "/healthz")
            ws = (h.get("fleet") or {}).get("workers", [])
            respawned = any(w["launch"] >= 2 and w["alive"] for w in ws)
            time.sleep(0.05)
        assert respawned, "killed worker never respawned"
        # traffic still flows, values still bitwise
        st, e, _ = _get_retry(base, "/v1/entry?i=0&j=1")
        assert st == 200
        assert np.float32(e["value"]) == np.float32(ref[0, 1])
    finally:
        out, err = _terminate_fleet(proc)
    assert proc.returncode == 0, err
    assert json.loads(out.strip().splitlines()[-1])["drained"] is True
    # the run dir tells the whole story
    s = summarize(run_dir)
    assert len(s["worker_launches"]) >= 3      # 2 initial + 1 respawn
    assert len(s["worker_deaths"]) >= 1
    assert s["fleet_drained"] is True
    assert not s["fleet_poisoned"]
    # and `dcfm-tpu events` narrates it
    cp = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.cli", "events", run_dir],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 0, cp.stderr
    assert "serve worker deaths" in cp.stdout
    assert "fleet drained cleanly" in cp.stdout


def test_fleet_poison_detection_on_instant_deaths(tmp_path):
    """Workers that die on arrival every launch are deterministic
    breakage: the fleet backs off, trips poison detection, and exits 2
    with a typed JSON line instead of relaunching forever."""
    run_dir = str(tmp_path / "obs")
    proc, info = _spawn_fleet(
        str(tmp_path / "no-such-artifact"), run_dir, workers=2,
        # min-uptime 10s: interpreter startup + the instant ArtifactError
        # still counts as an on-arrival death
        extra=["--fleet-poison-deaths", "2", "--fleet-min-uptime", "10"])
    # no SIGTERM: the fleet must give up BY ITSELF, bounded
    try:
        out, err = proc.communicate(timeout=60.0)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise AssertionError("poisoned fleet never gave up")
    assert proc.returncode == 2, (out, err)
    lines = [json.loads(ln) for ln in out.strip().splitlines()]
    assert any(ln.get("poisoned") for ln in lines), lines
    s = summarize(run_dir)
    assert s["fleet_poisoned"] is True
    assert len(s["worker_deaths"]) >= 2


# ---------------------------------------------------------------------------
# the serve chaos harness
# ---------------------------------------------------------------------------

def _run_chaos_point(tmp_path, seed, index):
    """One seeded chaos point end to end: build the promotion root,
    export the fault plan to a REAL 2-worker fleet, drive the loadgen
    (with the point's slow-loris clients), optionally promote mid-load
    (optionally a corrupted candidate), then drain under a hard bound.
    Asserts the sweep contract: every response ok or typed, zero
    dropped, zero generation regressions, fleet exits 0, never hangs."""
    spec = serve_fuzz_spec(seed, index, workers=2, max_requests=30)
    sv = spec["serve"]
    root = str(tmp_path / f"root{index}")
    os.makedirs(root)
    v1 = _make_artifact(os.path.join(root, "v1"), seed=100 + index)
    promote_artifact(root, "v1")
    v2 = _variant_artifact(v1, os.path.join(root, "v2"))
    if sv["promotion_fault"] == "torn":
        size = os.path.getsize(os.path.join(v2, MEAN_PANELS_FILE))
        with open(os.path.join(v2, MEAN_PANELS_FILE), "r+b") as f:
            f.truncate(size // 2)
    elif sv["promotion_fault"] == "bit_flip":
        _flip_byte(os.path.join(v2, MEAN_PANELS_FILE))
    run_dir = str(tmp_path / f"obs{index}")
    proc, info = _spawn_fleet(
        root, run_dir, workers=2,
        extra=["--io-timeout", "1.0", "--fleet-watchdog", "300"],
        env_extra={"DCFM_FAULT_PLAN": json.dumps(spec)})
    timer = None
    try:
        base = info["serving"]
        if sv["promote"]:
            timer = threading.Timer(
                0.3, lambda: promote_artifact(
                    root, "v2", verify=not sv["promotion_fault"]))
            timer.start()
        res = run_load(base, threads=6, requests_per_thread=10,
                       seed=seed * 1000 + index, p=P_ORIG, retries=10,
                       timeout=30.0, slow_clients=sv["slow_clients"],
                       slow_hold_s=3.0)
        if timer is not None:
            timer.join()
    finally:
        if timer is not None:
            timer.cancel()
        out, err = _terminate_fleet(proc, timeout=120.0)
    assert proc.returncode == 0, (sv, err[-2000:])
    assert res["untyped"] == [], (sv, res["untyped"][:3])
    assert res["dropped"] == 0, (sv, res)
    assert res["generation"]["violations"] == 0, (sv, res)
    if sv["promotion_fault"]:
        # every worker must have refused the corrupt candidate: no
        # response was ever tagged with the poisoned generation
        assert res["generation"]["max"] in (None, 1), (sv, res)
    return res, spec


def test_serve_chaos_smoke(tmp_path):
    """Tier-1 smoke: the first three DISTINCT chaos shapes of the
    seed-0 stream, through the full subprocess fleet harness."""
    picked, kinds = [], set()
    for idx in range(40):
        kind = serve_fuzz_spec(0, idx)["serve"]["kind"]
        if kind not in kinds:
            kinds.add(kind)
            picked.append(idx)
        if len(picked) == 3:
            break
    for idx in picked:
        _run_chaos_point(tmp_path, 0, idx)


@pytest.mark.slow
@pytest.mark.parametrize("index", range(25))
def test_serve_chaos_sweep(tmp_path, index):
    """The >=25-point acceptance sweep (DCFM_SERVE_FUZZ_SEED reseeds
    the whole stream): 0 hangs, 0 dropped, 0 untyped, per-point."""
    seed = int(os.environ.get("DCFM_SERVE_FUZZ_SEED", "0"))
    _run_chaos_point(tmp_path, seed, index)
