"""HTTP serving layer: the REAL server on a loopback port.

Pins the acceptance surface of the serving subsystem end to end:
/v1/entry and /v1/block responses bitwise-equal to the offline
assembler on the same artifact, a 64-thread query storm against a
bounded queue with zero deadlocks and correct backpressure rejections
(on a p=50k-scale sparse artifact), deterministic 429s when the queue
is full, degraded-mode operation under DCFM_NATIVE_DISABLE=1, and
graceful drain on SIGTERM via the real CLI subprocess.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.serve.artifact import (
    create_sparse_artifact, export_fit_result)
from dcfm_tpu.serve.server import PosteriorServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(base, path, timeout=10):
    """-> (status, payload) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def art(tmp_path_factory):
    Y, _ = make_synthetic(n=50, p=24, k_true=3, seed=9)
    Y[:, 5] = 0.0
    cfg = FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.9,
                          posterior_sd=True),
        run=RunConfig(burnin=30, mcmc=30, thin=2, seed=0),
        backend=BackendConfig(fetch_dtype="quant8"))
    res = fit(Y, cfg)
    td = tmp_path_factory.mktemp("serve_http")
    a = export_fit_result(res, str(td / "art"))
    return a, a.assemble(), a.assemble(destandardize=False)


@pytest.fixture()
def server(art):
    a, _, _ = art
    srv = PosteriorServer(a, port=0, max_queue=256)
    host, port = srv.start()
    yield srv, f"http://{host}:{port}", art
    srv.close()


def test_entry_and_block_bitwise_over_http(server):
    _, base, (a, ref, ref_raw) = server
    rng = np.random.default_rng(0)
    for _ in range(50):
        i, j = (int(v) for v in rng.integers(0, a.p_original, 2))
        st, e = _get(base, f"/v1/entry?i={i}&j={j}")
        assert st == 200
        # json round-trips the float32 exactly (float32 -> float64 repr)
        assert np.float32(e["value"]) == np.float32(ref[i, j]), (i, j)
    st, e = _get(base, "/v1/entry?i=1&j=2&destandardize=0")
    assert st == 200 and np.float32(e["value"]) == np.float32(ref_raw[1, 2])
    # zero-column entries serve exact 0
    st, e = _get(base, "/v1/entry?i=5&j=9")
    assert st == 200 and e["value"] == 0.0
    st, b = _get(base, "/v1/block?rows=0:6&cols=3,7,11,22")
    assert st == 200
    vals = np.asarray(b["values"], np.float32)
    np.testing.assert_array_equal(
        vals, ref[np.ix_(b["rows"], b["cols"])].astype(np.float32))


def test_interval_healthz_metrics_and_errors(server):
    _, base, (a, ref, _) = server
    st, iv = _get(base, "/v1/interval?i=2&j=7&alpha=0.1")
    assert st == 200
    assert np.float32(iv["mean"]) == np.float32(ref[2, 7])
    assert iv["lo"] < iv["mean"] < iv["hi"] and iv["sd"] > 0
    st, h = _get(base, "/healthz")
    assert st == 200 and h["status"] in ("ok", "degraded")
    assert h["p"] == a.p_original and h["has_sd"]
    # errors are 4xx JSON, never a crash
    for path, code in [("/v1/entry?i=99999&j=0", 400),
                       ("/v1/entry?i=abc&j=0", 400),
                       ("/v1/entry?j=0", 400),
                       ("/v1/block?rows=&cols=1", 400),
                       ("/v1/block?rows=0:99999&cols=1", 400),
                       ("/v1/interval?i=0&j=0&alpha=2", 400),
                       ("/nope", 404)]:
        st, body = _get(base, path)
        assert st == code, (path, st, body)
        assert "error" in body
    st, m = _get(base, "/metrics")
    assert st == 200
    assert m["latency"]["/v1/entry"]["count"] >= 1
    assert {"hits", "misses", "evictions"} <= set(m["cache"])
    assert m["batcher"]["queue_capacity"] == 256
    assert m["statuses"].get("200", 0) >= 1


def test_block_size_cap_is_413(server):
    _, base, (a, _, _) = server
    # 24 x 24 is fine; force the cap with a tiny monkeypatched limit
    from dcfm_tpu.serve import server as srv_mod
    old = srv_mod.MAX_BLOCK_ENTRIES
    srv_mod.MAX_BLOCK_ENTRIES = 4
    try:
        st, body = _get(base, "/v1/block?rows=0:3&cols=0:3")
        assert st == 413 and "tile" in body["error"]
    finally:
        srv_mod.MAX_BLOCK_ENTRIES = old


def test_backpressure_rejects_with_429_and_retry(art):
    """Deterministic queue-full: the batch worker is gated shut, the
    bounded queue fills, and further requests get 429 + retry:true
    instead of hanging or growing the queue."""
    a, ref, _ = art
    srv = PosteriorServer(a, port=0, max_queue=2, max_batch=1)
    gate = threading.Event()
    real = srv.batcher.engine

    class Gated:
        def entries(self, queries):
            gate.wait(10.0)
            return real.entries(queries)

    srv.batcher.engine = Gated()
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        results = []

        def one():
            results.append(_get(base, "/v1/entry?i=1&j=2", timeout=15))

        threads = [threading.Thread(target=one) for _ in range(8)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while (srv.batcher.stats()["rejected"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(timeout=20)
        statuses = sorted(st for st, _ in results)
        assert statuses.count(429) >= 1
        for st, body in results:
            if st == 429:
                assert body["retry"] is True
            else:
                assert st == 200
                assert np.float32(body["value"]) == np.float32(ref[1, 2])
        assert srv.batcher.stats()["rejected"] == statuses.count(429)
    finally:
        gate.set()
        srv.close()


def test_storm_64_threads_on_p50k_artifact(tmp_path):
    """The scale acceptance: a p=50,000-scale artifact (sparse panels)
    behind the real HTTP server survives a 64-thread query storm against
    a bounded queue - zero deadlocks/crashes, every response either a
    bitwise-correct 200 or an explicit 429 backpressure rejection."""
    path = create_sparse_artifact(str(tmp_path / "big"), g=100, P=500)
    # generous per-request deadline: this test pins deadlock-freedom and
    # backpressure correctness, not the loaded CI box's latency (the
    # default 2 s deadline legitimately 504s under a 64-thread storm on
    # one oversubscribed core; deadline semantics have their own test)
    srv = PosteriorServer(path, port=0, max_queue=128, max_batch=64,
                          cache_bytes=64 << 20, request_timeout=60.0)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    outcomes = {"ok": 0, "rejected": 0, "bad": []}
    lock = threading.Lock()
    try:
        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(10):
                i, j = (int(v) for v in rng.integers(0, 50_000, 2))
                st, body = _get(base, f"/v1/entry?i={i}&j={j}", timeout=30)
                with lock:
                    if st == 200 and body["value"] == 0.0:
                        outcomes["ok"] += 1    # hole-backed panels are 0
                    elif st == 429 and body.get("retry"):
                        outcomes["rejected"] += 1
                    else:
                        outcomes["bad"].append((st, body))

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(64)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(not t.is_alive() for t in threads), "storm deadlocked"
        assert outcomes["bad"] == []
        assert outcomes["ok"] + outcomes["rejected"] == 64 * 10
        assert outcomes["ok"] > 0
        st, m = _get(base, "/metrics")
        assert st == 200
        assert m["batcher"]["served"] == outcomes["ok"]
        assert m["batcher"]["rejected"] == outcomes["rejected"]
        assert m["batcher"]["queue_depth"] == 0
        assert time.monotonic() - t0 < 120
    finally:
        srv.close()


def _spawn_cli_serve(artifact_path, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "dcfm_tpu.cli", "serve",
         artifact_path, "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env)
    line = proc.stdout.readline()
    assert line, proc.stderr.read()
    return proc, json.loads(line)["serving"]


def test_cli_serve_drains_gracefully_on_sigterm(art):
    a, ref, _ = art
    proc, base = _spawn_cli_serve(a.path)
    try:
        st, e = _get(base, "/v1/entry?i=0&j=1", timeout=15)
        assert st == 200
        assert np.float32(e["value"]) == np.float32(ref[0, 1])
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, err
        assert json.loads(out.strip().splitlines()[-1])["drained"] is True
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_degraded_mode_serves_identical_values(art):
    """DCFM_NATIVE_DISABLE=1: /healthz reports degraded, every query
    keeps working through the pure-NumPy path, and the values are the
    SAME BITS the native-assembler server returns (the engine is
    native-independent by construction)."""
    a, ref, _ = art
    proc, base = _spawn_cli_serve(a.path,
                                  extra_env={"DCFM_NATIVE_DISABLE": "1"})
    try:
        st, h = _get(base, "/healthz", timeout=15)
        assert st == 200
        assert h["status"] == "degraded" and h["native"] is False
        rng = np.random.default_rng(3)
        for _ in range(20):
            i, j = (int(v) for v in rng.integers(0, a.p_original, 2))
            st, e = _get(base, f"/v1/entry?i={i}&j={j}", timeout=15)
            assert st == 200
            assert np.float32(e["value"]) == np.float32(ref[i, j])
        st, b = _get(base, "/v1/block?rows=0:5&cols=0:5", timeout=15)
        assert st == 200
        np.testing.assert_array_equal(
            np.asarray(b["values"], np.float32),
            ref[:5, :5].astype(np.float32))
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, err
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_close_persists_hotset_and_restart_prewarms(art, tmp_path):
    """A worker's close() writes this generation's hot set beside its
    artifact; a restarted worker on the same artifact pre-warms from it
    - first queries after a restart hit the cache instead of paying
    dequantizes.  (The swap-time half of the pre-warmer is pinned in
    test_serve_fleet.)"""
    import shutil

    from dcfm_tpu.serve.server import _hotset_path

    a, ref, _ = art
    path = str(tmp_path / "art")      # private copy: the hotset file
    shutil.copytree(a.path, path)     # lands beside the artifact
    srv = PosteriorServer(path, port=0)
    srv.start()
    try:
        assert srv._prewarmed == 0    # nothing persisted yet
        for _ in range(5):
            srv.handle("/v1/entry", {"i": ["0"], "j": ["1"]})
    finally:
        srv.close()
    assert os.path.exists(_hotset_path(path))

    srv2 = PosteriorServer(path, port=0)
    srv2.start()
    try:
        assert srv2._prewarmed >= 1
        before = srv2.engine.stats()
        st, e, _ = srv2.handle("/v1/entry", {"i": ["0"], "j": ["1"]})
        assert st == 200
        assert np.float32(e["value"]) == np.float32(ref[0, 1])
        after = srv2.engine.stats()
        assert after["misses"] == before["misses"]   # served warm
    finally:
        srv2.close()
