"""Mesh-parallel tests on the 8-virtual-device CPU mesh (SURVEY.md section 4
"Distributed-without-a-cluster").

Exercises the real `shard_map` code path: psum in the X update, all_gather
in the combine, per-device RNG offsets - and pins that it reproduces the
single-device vmap layout (which is itself pinned to the NumPy twin).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.models.conditionals import local_sum
from dcfm_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shards_per_device

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual CPU devices")


def _run(Y, m, r, mesh_devices=0):
    return fit(Y, FitConfig(
        model=m, run=r, backend=BackendConfig(mesh_devices=mesh_devices)))


def test_mesh_matches_vmap_one_shard_per_device():
    Y, _ = make_synthetic(80, 160, 4, seed=2)
    m = ModelConfig(num_shards=8, factors_per_shard=3, rho=0.9)
    r = RunConfig(burnin=15, mcmc=15, thin=1, seed=0)
    res1 = _run(Y, m, r)
    res8 = _run(Y, m, r, mesh_devices=8)
    np.testing.assert_allclose(
        res1.sigma_blocks, res8.sigma_blocks, rtol=1e-3, atol=1e-4)
    # final states match too (same RNG lineage on both layouts)
    np.testing.assert_allclose(
        np.asarray(res1.state.Lambda), np.asarray(res8.state.Lambda),
        rtol=1e-3, atol=1e-4)


def test_mesh_matches_vmap_multiple_shards_per_device():
    """config-5 layout: more shards than devices -> vmap within shard_map."""
    Y, _ = make_synthetic(60, 160, 4, seed=4)
    m = ModelConfig(num_shards=16, factors_per_shard=2, rho=0.8)
    r = RunConfig(burnin=10, mcmc=10, thin=1, seed=1)
    res1 = _run(Y, m, r)
    res8 = _run(Y, m, r, mesh_devices=8)
    np.testing.assert_allclose(
        res1.sigma_blocks, res8.sigma_blocks, rtol=1e-3, atol=1e-4)


def test_mesh_dl_prior_statistically_equivalent():
    """The DL prior's GIG rejection while_loop composed under vmap inside
    shard_map.  Unlike the MGP chain, bitwise layout equality is not a
    design guarantee here: psum's reduction order differs from jnp.sum by
    ulps, and one flipped accept/reject in the GIG sampler lawfully swaps
    in a different draw.  The pin is statistical: both layouts recover the
    same truth to the same accuracy."""
    Y, St = make_synthetic(120, 64, 3, seed=8)
    m = ModelConfig(num_shards=4, factors_per_shard=3, rho=0.8, prior="dl")
    r = RunConfig(burnin=80, mcmc=80, thin=1, seed=3)
    res1 = _run(Y, m, r)
    res4 = _run(Y, m, r, mesh_devices=4)

    def err(res):
        return (np.linalg.norm(res.Sigma - St) / np.linalg.norm(St))

    e1, e4 = err(res1), err(res4)
    assert np.isfinite(res4.Sigma).all()
    assert e1 < 0.4 and e4 < 0.4
    assert abs(e1 - e4) < 0.1


@pytest.mark.slow
def test_mesh_dl_prior_long_chain_halved_bounds():
    """Slow-lane DL mesh-equivalence pin at HALVED tolerances (round-4
    verdict: a bug that only manifests after a GIG accept/reject flip and
    costs <= 0.1 rel err passed both fast-lane tests).  Bitwise layout
    equality is unattainable by construction - the X-update psum's
    reduction order differs from the vmap layout's jnp.sum by ulps, and
    the GIG sampler's accept/reject comparison is discontinuous in its
    parameters, so one ulp lawfully swaps in a different (equally valid)
    draw after a few sweeps.  What CAN be tightened is the statistical
    bound: with 3x the draws of the fast-lane test, Monte Carlo error
    shrinks enough that both layouts must recover the truth to err < 0.3
    and agree to |Δerr| < 0.05 - half the fast-lane bounds, so a layout
    bug half the size of anything the fast lane would catch fails here."""
    Y, St = make_synthetic(120, 64, 3, seed=8)
    m = ModelConfig(num_shards=4, factors_per_shard=3, rho=0.8, prior="dl")
    r = RunConfig(burnin=200, mcmc=280, thin=1, seed=3)
    res1 = _run(Y, m, r)
    res4 = _run(Y, m, r, mesh_devices=4)

    def err(res):
        return (np.linalg.norm(res.Sigma - St) / np.linalg.norm(St))

    e1, e4 = err(res1), err(res4)
    assert np.isfinite(res4.Sigma).all()
    assert e1 < 0.3 and e4 < 0.3, (e1, e4)
    assert abs(e1 - e4) < 0.05, (e1, e4)


def test_mesh_dl_prior_short_chain_tight():
    """Tight DL mesh-layout pin, complementing the statistical test above:
    over a FEW sweeps the psum reduction-order ulps cannot have flipped a
    GIG accept/reject branch yet (deterministic for a fixed seed on the
    virtual mesh), so mesh and vmap layouts must agree to float noise.
    A DL mesh-layout bug costing even ~0.01 rel err fails here, where the
    statistical tolerances above would let it through."""
    Y, _ = make_synthetic(60, 64, 3, seed=9)
    m = ModelConfig(num_shards=4, factors_per_shard=3, rho=0.8, prior="dl")
    r = RunConfig(burnin=1, mcmc=2, thin=1, seed=5)
    res1 = _run(Y, m, r)
    res4 = _run(Y, m, r, mesh_devices=4)
    np.testing.assert_allclose(res1.sigma_blocks, res4.sigma_blocks,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(res1.state.Lambda), np.asarray(res4.state.Lambda),
        rtol=1e-4, atol=1e-5)


def test_combine_chunks_matches_single_shot():
    """ModelConfig.combine_chunks (the pod-scale determinism knob) splits
    the per-draw combine into column chunks with a psum rendezvous between
    them; the accumulated panels must match the single-shot combine on both
    layouts."""
    import dataclasses

    Y, _ = make_synthetic(50, 64, 3, seed=6)
    m1 = ModelConfig(num_shards=8, factors_per_shard=2, rho=0.8,
                     posterior_sd=True)
    m2 = dataclasses.replace(m1, combine_chunks=4)
    r = RunConfig(burnin=10, mcmc=10, thin=2, seed=2)
    res1 = _run(Y, m1, r)
    res2 = _run(Y, m2, r)
    np.testing.assert_allclose(res1.sigma_blocks, res2.sigma_blocks,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(res1.sd_upper_panels, res2.sd_upper_panels,
                               rtol=1e-4, atol=1e-5)
    res_mesh = _run(Y, m2, r, mesh_devices=4)
    np.testing.assert_allclose(res1.sigma_blocks, res_mesh.sigma_blocks,
                               rtol=1e-3, atol=1e-4)


def test_combine_chunks_plain_estimator():
    """The plain (reference-rule) estimator's diagonal-block selection must
    survive column chunking (the diag one-hot shifts per chunk)."""
    import dataclasses

    Y, _ = make_synthetic(40, 48, 2, seed=11)
    m1 = ModelConfig(num_shards=6, factors_per_shard=2, rho=0.7,
                     estimator="plain")
    m2 = dataclasses.replace(m1, combine_chunks=3)
    r = RunConfig(burnin=8, mcmc=8, thin=2, seed=1)
    res1 = _run(Y, m1, r)
    res2 = _run(Y, m2, r)
    np.testing.assert_allclose(res1.sigma_blocks, res2.sigma_blocks,
                               rtol=1e-5, atol=1e-6)


def test_mesh_with_two_devices():
    Y, _ = make_synthetic(50, 64, 3, seed=6)
    m = ModelConfig(num_shards=4, factors_per_shard=2, rho=0.7)
    r = RunConfig(burnin=15, mcmc=15, thin=1, seed=2)
    res1 = _run(Y, m, r)
    res2 = _run(Y, m, r, mesh_devices=2)
    np.testing.assert_allclose(
        res1.sigma_blocks, res2.sigma_blocks, rtol=1e-3, atol=1e-4)


def test_psum_equals_serial_sum():
    """Property test from SURVEY.md section 4: the mesh psum equals the
    serial over-shards sum the reference computes at divideconquer.m:112-116.
    """
    from jax.sharding import PartitionSpec as P

    from dcfm_tpu.parallel.shard import shard_map

    mesh = make_mesh(8)
    x = np.random.default_rng(0).normal(size=(8, 4, 5)).astype(np.float32)

    def f(xl):
        return jax.lax.psum(jnp.sum(xl, axis=0), SHARD_AXIS)

    out = jax.jit(shard_map(
        f, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P()))(x)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_shards_per_device_validation():
    mesh = make_mesh(8)
    assert shards_per_device(16, mesh) == 2
    with pytest.raises(ValueError):
        shards_per_device(12, mesh)


def test_mesh_requires_enough_devices():
    Y, _ = make_synthetic(30, 32, 2, seed=8)
    m = ModelConfig(num_shards=4, factors_per_shard=2, rho=0.5)
    r = RunConfig(burnin=5, mcmc=5, thin=1, seed=0)
    with pytest.raises(ValueError, match="mesh_devices"):
        _run(Y, m, r, mesh_devices=64)
