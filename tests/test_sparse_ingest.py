"""Scale-out ingestion tests: sparse/CSR Y, out-of-core preprocessing,
lazy results, and cooperative artifact assembly.

The contract under test is BITWISE equality: the streaming preprocess
mirrors the dense pipeline's exact operation order (same rng draws, same
reduction axes, same final cast), so a densified sparse input must
produce byte-identical shard blocks, stats, and - through a short fit -
byte-identical posterior panels and (under materialize_sigma='always')
the byte-identical dense Sigma.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dcfm_tpu.api import fit
from dcfm_tpu.config import FitConfig, ModelConfig, RunConfig
from dcfm_tpu.utils.preprocess import (
    LazyMaterializationError, SparseMatrix, is_streaming_input, preprocess,
    restore_covariance, restore_data_matrix)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _csr_from_dense(Y):
    """Dependency-free CSR triple that keeps stored NaNs AND treats the
    dense array's zeros as implicit (not stored) - the canonical
    densify-inverse used for the parity tests."""
    n, p = Y.shape
    indptr = np.zeros(n + 1, np.int64)
    indices, data = [], []
    for i in range(n):
        row = Y[i]
        nz = np.flatnonzero((row != 0) | np.isnan(row))
        indices.append(nz)
        data.append(row[nz])
        indptr[i + 1] = indptr[i] + nz.size
    return SparseMatrix(indptr, np.concatenate(indices),
                        np.concatenate(data), (n, p), format="csr")


def _toy(rng, n=40, p=36, *, nan=True, zero_col=True):
    Y = rng.normal(size=(n, p))
    Y[Y < -0.5] = 0.0
    if nan:
        Y[0, 3] = np.nan
        Y[5, 11] = np.nan
    if zero_col:
        Y[:, 7] = 0.0
    return Y


CFG = FitConfig(
    model=ModelConfig(num_shards=4, factors_per_shard=3, rho=0.5),
    run=RunConfig(burnin=10, mcmc=20, thin=2, seed=3))


# ---------------------------------------------------------------------------
# streaming preprocess: bitwise parity with the dense pipeline
# ---------------------------------------------------------------------------

def _assert_pre_equal(pre_d, pre_s):
    assert pre_s.is_lazy and not pre_d.is_lazy
    np.testing.assert_array_equal(pre_d.perm, pre_s.perm)
    np.testing.assert_array_equal(pre_d.kept_cols, pre_s.kept_cols)
    np.testing.assert_array_equal(pre_d.zero_cols, pre_s.zero_cols)
    assert pre_d.n_missing == pre_s.n_missing
    np.testing.assert_array_equal(pre_d.col_mean, pre_s.col_mean)
    np.testing.assert_array_equal(pre_d.col_scale, pre_s.col_scale)
    dense = pre_s.data.materialize()
    assert dense.dtype == pre_d.data.dtype
    np.testing.assert_array_equal(pre_d.data, dense)


def test_csr_preprocess_bitwise_equals_dense(rng):
    Y = _toy(rng)
    pre_d = preprocess(Y, 4, seed=2)
    pre_s = preprocess(_csr_from_dense(Y), 4, seed=2)
    _assert_pre_equal(pre_d, pre_s)


def test_csc_and_scipy_inputs_match_dense(rng):
    sp = pytest.importorskip("scipy.sparse")
    Y = _toy(rng, nan=False)   # scipy csr_matrix(dense) drops NaNs' zeros
    pre_d = preprocess(Y, 4, seed=5)
    csr = _csr_from_dense(Y)
    from dcfm_tpu.utils.preprocess import _csr_to_csc
    indptr, indices, data = _csr_to_csc(
        csr.indptr, csr.indices, csr.data, csr.shape)
    csc = SparseMatrix(indptr, indices, data, csr.shape, format="csc")
    _assert_pre_equal(pre_d, preprocess(csc, 4, seed=5))
    _assert_pre_equal(pre_d, preprocess(sp.csr_matrix(Y), 4, seed=5))


def test_nan_vs_explicit_zero_semantics(rng):
    """Stored NaN = missing (imputed); explicit stored zero behaves
    exactly like a dense zero - a column of only stored zeros is dropped
    with the all-zero columns."""
    n, p = 12, 8
    Y = rng.normal(size=(n, p))
    Y[:, 2] = 0.0
    Y[0, 5] = np.nan
    csr = _csr_from_dense(Y)
    # add explicit stored zeros into column 2 (dense densify drops them)
    extra_rows = [1, 4]
    indptr = csr.indptr.copy()
    indices, data = list(csr.indices), list(csr.data)
    for r in sorted(extra_rows, reverse=True):
        at = int(np.searchsorted(indices[indptr[r]:indptr[r + 1]], 2)
                 + indptr[r])
        indices.insert(at, 2)
        data.insert(at, 0.0)
        indptr[r + 1:] += 1
    stuffed = SparseMatrix(indptr, np.array(indices), np.array(data),
                           (n, p), format="csr")
    pre_d = preprocess(Y, 2, seed=0)
    pre_s = preprocess(stuffed, 2, seed=0)
    _assert_pre_equal(pre_d, pre_s)      # the stored zeros changed nothing
    assert 2 in pre_s.zero_cols          # still dropped
    assert pre_s.n_missing == 1          # the NaN is missing, zeros are data


def test_memmap_input_streams(rng, tmp_path):
    Y = _toy(rng)
    path = tmp_path / "y.npy"
    np.save(path, Y)
    Ymm = np.load(path, mmap_mode="r")
    assert is_streaming_input(Ymm)
    pre_d = preprocess(Y, 4, seed=2)
    pre_s = preprocess(Ymm, 4, seed=2)
    _assert_pre_equal(pre_d, pre_s)


def test_inf_refused_on_streaming_path(rng):
    Y = _toy(rng, nan=False)
    Y[1, 1] = np.inf
    with pytest.raises(ValueError, match="infinite"):
        preprocess(_csr_from_dense(Y), 4, seed=0)


def test_lazy_restores_refuse_with_typed_error(rng):
    Y = _toy(rng)
    pre = preprocess(_csr_from_dense(Y), 4, seed=2)
    S = np.eye(pre.p_used, dtype=np.float32)
    with pytest.raises(LazyMaterializationError, match="materialize_sigma"):
        restore_covariance(S, pre)
    with pytest.raises(LazyMaterializationError, match="materialize_sigma"):
        restore_data_matrix(np.zeros(pre.data.shape, np.float32), pre)
    # force=True is the explicit escape hatch
    out = restore_covariance(S, pre, force=True)
    assert out.shape == (pre.p_used - pre.n_pad,) * 2


# ---------------------------------------------------------------------------
# fit: lazy results, sigma_block, and bitwise sparse/dense parity
# ---------------------------------------------------------------------------

def test_sparse_fit_bitwise_matches_dense(rng):
    Y = _toy(rng)
    res_d = fit(Y, CFG)
    res_s = fit(_csr_from_dense(Y), CFG)
    assert res_d.Sigma is not None      # dense auto materializes
    assert res_s.Sigma is None          # lazy auto does not
    np.testing.assert_array_equal(res_d.upper_panels, res_s.upper_panels)
    # the explicit opt-in reproduces the dense Sigma bit-for-bit
    res_a = fit(_csr_from_dense(Y),
                dataclasses.replace(CFG, materialize_sigma="always"))
    np.testing.assert_array_equal(res_d.Sigma, res_a.Sigma)


def test_sigma_block_serves_lazy_posterior(rng):
    from dcfm_tpu.utils.estimate import full_blocks_from_upper
    Y = _toy(rng)
    res = fit(_csr_from_dense(Y), CFG)
    g = CFG.model.num_shards
    blocks = full_blocks_from_upper(res.upper_panels, g)
    scale = np.asarray(res.preprocess.col_scale, np.float32)
    for i, j in [(0, 0), (1, 3), (3, 1), (2, 2)]:
        want = blocks[i, j] * (scale[i][:, None] * scale[j][None, :])
        np.testing.assert_array_equal(res.sigma_block(i, j), want)
    # (j, i) is exactly the transpose of (i, j)
    np.testing.assert_array_equal(res.sigma_block(3, 1),
                                  res.sigma_block(1, 3).T)
    with pytest.raises(IndexError):
        res.sigma_block(0, g)


def test_lazy_result_refusals_and_artifact_export(rng, tmp_path):
    Y = _toy(rng)
    res = fit(_csr_from_dense(Y), CFG)
    with pytest.raises(LazyMaterializationError, match="materialize_sigma"):
        res.covariance()
    # the serve artifact needs no dense Sigma
    art = res.export_artifact(str(tmp_path / "art"))
    assert art.meta["p_original"] == Y.shape[1]


def test_materialize_never_on_dense_input(rng):
    Y = _toy(rng, nan=False)
    res = fit(Y, dataclasses.replace(CFG, materialize_sigma="never"))
    assert res.Sigma is None
    # an EAGER pre still answers an explicit covariance() query
    C = res.covariance(reinsert_zero_cols=True)
    res_d = fit(Y, CFG)
    np.testing.assert_array_equal(C, res_d.Sigma)


def test_materialize_sigma_validated():
    with pytest.raises(ValueError, match="materialize_sigma"):
        fit(np.zeros((4, 4)) + 1.0,
            dataclasses.replace(CFG, materialize_sigma="sometimes"))


# ---------------------------------------------------------------------------
# peak-RSS regression guard: streaming ingest never densifies
# ---------------------------------------------------------------------------

_RSS_PROBE = r"""
import json, resource, sys
import numpy as np
sys.path.insert(0, {repo!r})
from dcfm_tpu.utils.preprocess import SparseMatrix, preprocess

n, p, g = 16, 800_000, 200
rng = np.random.default_rng(0)
nnz_per_row = p // 300
indptr = np.arange(n + 1, dtype=np.int64) * nnz_per_row
indices = np.concatenate(
    [np.sort(rng.choice(p, nnz_per_row, replace=False)) for _ in range(n)])
data = rng.standard_normal(indices.size)
Y = SparseMatrix(indptr, indices, data, (n, p), format="csr")
before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
pre = preprocess(Y, g, seed=0)
for s in range(g):                  # stream every shard block once
    pre.data.block(s)
after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({{"delta_kb": int(after - before)}}))
"""


@pytest.mark.slow
def test_streaming_ingest_peak_rss_stays_bounded(tmp_path):
    """At a toy-wide shape (p=800k, n=16) the dense pipeline would hold
    the (n, p) float64 matrix (~100 MB) plus the (g, n, P) float32
    tensor (~51 MB); the streaming path touches O(p) stats and one
    (n, P) block (~0.25 MB) at a time.  The guard bounds the streaming
    path's RSS growth at a fraction of the dense tensor alone, so any
    regression that densifies inside _preprocess_streaming trips it.
    ru_maxrss is a process-lifetime high-water mark, so the probe runs
    in its own subprocess with the baseline taken after input build."""
    probe = tmp_path / "rss_probe.py"
    probe.write_text(_RSS_PROBE.format(repo=REPO))
    out = subprocess.run(
        [sys.executable, str(probe)], capture_output=True,
        text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    sparse_kb = json.loads(out.stdout)["delta_kb"]
    # the (g, n, P) float32 tensor alone is ~51 MB; half of it is
    # generous headroom for allocator noise while still catching any
    # dense materialization
    assert sparse_kb < 24_000, f"streaming ingest peaked at {sparse_kb} kB"


# ---------------------------------------------------------------------------
# cooperative (multi-host) artifact assembly
# ---------------------------------------------------------------------------

def test_cooperative_pair_slice_partitions_exactly():
    from dcfm_tpu.serve.artifact import cooperative_pair_slice
    for n_pairs in (1, 7, 10, 55):
        for pc in (1, 2, 3, 8):
            spans = [cooperative_pair_slice(n_pairs, i, pc)
                     for i in range(pc)]
            assert spans[0][0] == 0 and spans[-1][1] == n_pairs
            for (alo, ahi), (blo, bhi) in zip(spans, spans[1:]):
                assert ahi == blo


def test_two_process_cooperative_export_byte_identical(rng, tmp_path):
    """Two 'hosts' (threads with a real barrier - the phase protocol is
    what multihost_utils.sync_global_devices provides on a pod) writing
    their pair slices + the host-0 finalize produce byte-identical
    panel binaries and meta.json to the single-host export, and the
    stitched artifact passes the full promotion CRC sweep."""
    import threading

    from dcfm_tpu.serve.artifact import (
        MEAN_PANELS_FILE, META_FILE, export_fit_result,
        export_fit_result_cooperative)
    from dcfm_tpu.serve.promote import verify_candidate

    Y = _toy(rng)
    res = fit(_csr_from_dense(Y), CFG)
    single = str(tmp_path / "single")
    coop = str(tmp_path / "coop")
    export_fit_result(res, single)
    sync = threading.Barrier(2, timeout=60)
    tags, errs = [], []

    def host(pi):
        try:
            export_fit_result_cooperative(
                res, coop, process_index=pi, process_count=2,
                barrier=lambda tag: (tags.append(tag), sync.wait()))
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)
            sync.abort()

    threads = [threading.Thread(target=host, args=(pi,)) for pi in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        # unbounded join is safe: the Barrier's own timeout=60 breaks any
        # stuck phase, which aborts both hosts into errs
        t.join()
    assert not errs, errs
    # each of the three phase barriers fired once per host
    assert len(tags) == 6 and len(set(tags)) == 3
    for name in (MEAN_PANELS_FILE, META_FILE):
        a = open(os.path.join(single, name), "rb").read()
        b = open(os.path.join(coop, name), "rb").read()
        assert a == b, f"{name} differs between single-host and cooperative"
    art = verify_candidate(coop)      # full per-panel CRC sweep
    assert art.fingerprint == verify_candidate(single).fingerprint
