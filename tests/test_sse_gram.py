"""Gram-based SSE psi path (BackendConfig.sse_mode).

Pins the whole sse_mode contract of the fused sweep:

* the "resid" default is INERT - a config that never mentions sse_mode
  traces the identical sweep jaxpr and fits bitwise-identically to an
  explicit "resid" request (the knob is guarded at trace time, so the
  default compiles the pre-knob program);
* "gram" replaces the (n, P) residual SSE with the Gram identity
  SSE_j = Y_j'Y_j - 2 Lam_j'(EY)_j + Lam_j' E Lam_j on the Lambda
  stage's cross-moments, within a pinned f32 error band of the residual
  formula (the cancellation is real but bounded), and the gram fit
  lands inside the measured cross-seed MC spread of resid f32 fits;
* under MGP adaptive truncation the masked Gram SSE is EXACTLY the
  truncated one - inactive columns contribute literal zeros to both
  contractions - so rank adaptation and sse_mode="gram" compose;
* the fused per-feature kernel (ops/sse_gamma) is BITWISE-identical to
  its scan-tiled fallback where the kernel exists (K <= 16) and
  numerically correct at every K;
* sse_mode rides checkpoints as metadata only: the carry layout is mode
  independent, so a resume may flip the mode freely (unlike
  compute_dtype) and the donor's mode stays in the meta record;
* the rejection-free Exp-sum Gamma draw (ops/gamma.gamma_unit_static)
  has the right moments at the integer / half-integer shapes the psi
  stage uses.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import make_synthetic

from dcfm_tpu import BackendConfig, FitConfig, ModelConfig, RunConfig, fit
from dcfm_tpu.models.conditionals import resolve_sse_mode
from dcfm_tpu.ops.gamma import gamma_unit_static
from dcfm_tpu.ops.sse_gamma import gram_sse_ps


def _cfg(sse_mode=None, *, seed=0, chunk=0, **kw):
    backend = BackendConfig() if sse_mode is None else BackendConfig(
        sse_mode=sse_mode)
    return FitConfig(
        model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.8),
        run=RunConfig(burnin=16, mcmc=16, thin=2, seed=seed,
                      chunk_size=chunk),
        backend=backend, **kw)


@pytest.fixture(scope="module")
def data():
    Y, St = make_synthetic(n=40, p=24, k_true=3, seed=7)
    return Y, St


# ---------------------------------------------------------------------------
# resid default is inert
# ---------------------------------------------------------------------------

def test_resid_default_bitwise_identical(data):
    """The knob's default must change NOTHING: a config that never
    mentions sse_mode and one that asks for "resid" explicitly are the
    same program - Sigma, traces, and final state bitwise equal."""
    Y, _ = data
    res_default = fit(Y, _cfg(None))
    res_resid = fit(Y, _cfg("resid"))
    np.testing.assert_array_equal(res_default.Sigma, res_resid.Sigma)
    np.testing.assert_array_equal(res_default.traces, res_resid.traces)
    np.testing.assert_array_equal(np.asarray(res_default.state.ps),
                                  np.asarray(res_resid.state.ps))


def _sweep_jaxpr(sse_mode, *, n=8, K=3, default=False):
    from dcfm_tpu.models.conditionals import gibbs_sweep
    from dcfm_tpu.models.priors import make_prior
    from dcfm_tpu.models.state import init_state

    kw = {} if default else {"sse_mode": sse_mode}
    cfg = ModelConfig(num_shards=2, factors_per_shard=K, rho=0.8, **kw)
    prior = make_prior(cfg)
    key = jax.random.key(0)
    state = init_state(key, prior, num_local_shards=2, n=n, P=6, K=K,
                       as_=cfg.as_, bs=cfg.bs)
    Y = jnp.zeros((2, n, 6), jnp.float32)
    return str(jax.make_jaxpr(
        lambda k, y, s: gibbs_sweep(k, y, s, cfg, prior))(key, Y, state))


def test_sweep_jaxpr_pins():
    """Graph-level pin of "the default compiles the pre-knob program":
    the no-knob jaxpr is byte-identical to the explicit-resid one, the
    gram jaxpr is a genuinely different program, "auto" resolves to gram
    at trace time when n >= K, and the gram f32 graph stays bf16-free
    (the Gram moments don't smuggle in reduced precision)."""
    jp_default = _sweep_jaxpr(None, default=True)
    jp_resid = _sweep_jaxpr("resid")
    jp_gram = _sweep_jaxpr("gram")
    assert jp_default == jp_resid
    assert jp_gram != jp_resid
    assert _sweep_jaxpr("auto", n=8, K=3) == jp_gram      # n >= K
    # n < K: auto falls back to resid (same-shape jaxprs compared)
    assert _sweep_jaxpr("auto", n=2, K=3) == _sweep_jaxpr("resid", n=2,
                                                          K=3)
    assert "bf16" not in jp_gram


def test_resolve_sse_mode():
    assert resolve_sse_mode("resid", n=1000, K=2) == "resid"
    assert resolve_sse_mode("gram", n=2, K=1000) == "gram"
    assert resolve_sse_mode("auto", n=16, K=16) == "gram"
    assert resolve_sse_mode("auto", n=15, K=16) == "resid"


def test_unknown_sse_mode_refused():
    """A typo'd mode is a typed refusal at validate time, on BOTH the
    user knob and the internal ModelConfig mirror."""
    from dcfm_tpu.config import validate

    bad_backend = dataclasses.replace(
        _cfg(None), backend=BackendConfig(sse_mode="cholesky"))
    with pytest.raises(ValueError, match="sse_mode"):
        validate(bad_backend, 40, 24)
    bad_model = dataclasses.replace(
        _cfg(None), model=ModelConfig(num_shards=2, factors_per_shard=3,
                                      rho=0.8, sse_mode="cholesky"))
    with pytest.raises(ValueError, match="sse_mode"):
        validate(bad_model, 40, 24)


# ---------------------------------------------------------------------------
# gram == resid up to a pinned f32 cancellation band
# ---------------------------------------------------------------------------

def _sse_problem(n, P, K, seed, noise=0.3):
    """Realistic operands: Y generated BY the factor model, so the SSE
    genuinely cancels (the Gram subtrahends are O(yty))."""
    r = np.random.default_rng(seed)
    eta = r.standard_normal((n, K)).astype(np.float32)
    Lam = (r.standard_normal((P, K)) / np.sqrt(K)).astype(np.float32)
    Y = (eta @ Lam.T
         + noise * r.standard_normal((n, P))).astype(np.float32)
    return jnp.asarray(Y), jnp.asarray(eta), jnp.asarray(Lam)


def _gram_operands(Y, eta, Lam):
    E = eta.T @ eta
    EY = eta.T @ Y
    return Lam @ E, EY.T, jnp.sum(Y * Y, axis=0)


@pytest.mark.parametrize("n,K", [(200, 16), (200, 128)])
def test_gram_sse_matches_resid_within_band(n, K):
    """The accuracy contract, pinned at the shipped error band: max
    relative gap between the Gram and residual SSE stays under 1e-4 in
    f32 (measured ~7e-6 at K=16 and ~2e-5 at K=128 on model-generated
    data; the bound leaves margin, not slack for a broken formula)."""
    Y, eta, Lam = _sse_problem(n, 300, K, seed=K)
    resid = Y - eta @ Lam.T
    sse_resid = np.asarray(jnp.sum(resid * resid, axis=0))
    M, EYt, yty = _gram_operands(Y, eta, Lam)
    gunit = jnp.ones((300,), jnp.float32)
    _, sse_gram = gram_sse_ps(Lam, M, EYt, yty, gunit, bs=0.3)
    rel = np.abs(np.asarray(sse_gram) - sse_resid) / np.maximum(
        sse_resid, 1e-9)
    assert rel.max() < 1e-4, f"max rel SSE gap {rel.max():.2e}"


def test_gram_fit_inside_resid_mc_band():
    """Run the SAME fit under several resid f32 seeds to measure the MC
    spread of rel-Frobenius error, then demand the gram fit land inside
    that band (widened by half its width for finite-sample slack): the
    two SSE strategies are statistically exchangeable, so the mode flip
    may move a fit within MC noise, never outside it."""
    Y, St = make_synthetic(n=120, p=48, k_true=3, seed=11)
    norm = np.linalg.norm(St)

    def run(sse_mode, seed):
        cfg = FitConfig(
            model=ModelConfig(num_shards=2, factors_per_shard=3, rho=0.8),
            run=RunConfig(burnin=150, mcmc=150, thin=1, seed=seed),
            backend=BackendConfig(sse_mode=sse_mode))
        return float(np.linalg.norm(fit(Y, cfg).Sigma - St) / norm)

    resid_errs = np.array([run("resid", s) for s in range(4)])
    gram_err = run("gram", 0)
    width = max(resid_errs.max() - resid_errs.min(), 1e-3)
    lo = resid_errs.min() - 0.5 * width
    hi = resid_errs.max() + 0.5 * width
    assert lo <= gram_err <= hi, (
        f"gram err {gram_err:.4f} outside resid MC band "
        f"[{lo:.4f}, {hi:.4f}] (resid samples {np.round(resid_errs, 4)})")


# ---------------------------------------------------------------------------
# MGP truncation: masked Gram SSE == truncated Gram SSE exactly
# ---------------------------------------------------------------------------

def test_masked_gram_sse_equals_truncated_exactly():
    """Adaptive truncation zeroes inactive Lambda columns (and masks
    eta's); every masked entry of E/EY then meets a zero factor in both
    length-K contractions, contributing literal float zeros - so the
    K-wide masked Gram SSE must equal the k_active-wide one BITWISE, not
    just approximately."""
    K, k_act = 8, 5
    Y, eta, Lam = _sse_problem(60, 96, K, seed=3)
    active = jnp.asarray((np.arange(K) < k_act).astype(np.float32))
    Lam_m = Lam * active[None, :]
    eta_m = eta * active[None, :]
    M, EYt, yty = _gram_operands(Y, eta_m, Lam_m)
    gunit = jnp.full((96,), 2.0, jnp.float32)
    ps_full, sse_full = gram_sse_ps(Lam_m, M, EYt, yty, gunit, bs=0.3,
                                    impl="plain")
    Mt, EYtt, _ = _gram_operands(Y, eta_m[:, :k_act], Lam_m[:, :k_act])
    ps_trunc, sse_trunc = gram_sse_ps(Lam_m[:, :k_act], Mt, EYtt, yty,
                                      gunit, bs=0.3, impl="plain")
    np.testing.assert_array_equal(np.asarray(sse_full),
                                  np.asarray(sse_trunc))
    np.testing.assert_array_equal(np.asarray(ps_full),
                                  np.asarray(ps_trunc))


def test_rank_adapt_gram_fit_runs():
    """sse_mode="gram" composes with MGP rank adaptation end to end: the
    adaptive fit runs and returns a finite posterior in the same
    accuracy class as the resid one."""
    Y, St = make_synthetic(n=60, p=24, k_true=2, seed=5)
    model = ModelConfig(num_shards=2, factors_per_shard=4, rho=0.8,
                        rank_adapt=True)
    run = RunConfig(burnin=40, mcmc=40, thin=2, seed=0)

    def err(sse_mode):
        cfg = FitConfig(model=model, run=run,
                        backend=BackendConfig(sse_mode=sse_mode))
        r = fit(Y, cfg)
        assert np.all(np.isfinite(r.Sigma))
        return float(np.linalg.norm(r.Sigma - St) / np.linalg.norm(St))

    e_gram, e_resid = err("gram"), err("resid")
    assert abs(e_gram - e_resid) < 0.5 * max(e_resid, 0.1)


# ---------------------------------------------------------------------------
# fused kernel: bitwise vs fallback, correct at every K
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("K", [4, 8, 16])
def test_kernel_bitwise_vs_fallback(K):
    """Where the fused kernel exists (K <= 16) the scan-tiled fallback
    must be BITWISE equal to pallas-interpret - it executes the kernel's
    own lane helper on the same tile slices, so they share every FMA
    contraction decision (see the ops/sse_gamma docstring on why the
    scan wrapper, not just the shared helper, is what pins this)."""
    r = np.random.default_rng(K)
    B = 700                                    # forces a padded tile
    Y, eta, Lam = _sse_problem(50, B, K, seed=K)
    M, EYt, yty = _gram_operands(Y, eta, Lam)
    gunit = jnp.asarray(r.gamma(5.0, size=B).astype(np.float32))
    ps_i, sse_i = gram_sse_ps(Lam, M, EYt, yty, gunit, bs=0.3,
                              impl="pallas-interpret")
    ps_u, sse_u = gram_sse_ps(Lam, M, EYt, yty, gunit, bs=0.3,
                              impl="unrolled")
    np.testing.assert_array_equal(np.asarray(ps_i), np.asarray(ps_u))
    np.testing.assert_array_equal(np.asarray(sse_i), np.asarray(sse_u))


@pytest.mark.parametrize("impl", ["plain", "unrolled", "auto"])
def test_kernel_correct_vs_reference(impl):
    """Every dispatch computes the documented formulas to f32 accuracy
    against a float64 reference (K = 128 exercises the K > 16 fallback
    of the non-plain impls)."""
    K = 12 if impl != "plain" else 128
    Y, eta, Lam = _sse_problem(40, 500, K, seed=1)
    M, EYt, yty = _gram_operands(Y, eta, Lam)
    r = np.random.default_rng(0)
    gunit = jnp.asarray(r.gamma(5.0, size=500).astype(np.float32))
    ps, sse = gram_sse_ps(Lam, M, EYt, yty, gunit, bs=0.3, impl=impl)
    L64, M64 = np.asarray(Lam, np.float64), np.asarray(M, np.float64)
    ref_sse = np.maximum(
        np.asarray(yty, np.float64)
        - 2.0 * np.sum(L64 * np.asarray(EYt, np.float64), axis=1)
        + np.sum(L64 * M64, axis=1), 0.0)
    ref_ps = np.asarray(gunit, np.float64) / (0.3 + 0.5 * ref_sse)
    np.testing.assert_allclose(np.asarray(sse), ref_sse,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ps), ref_ps,
                               rtol=1e-4, atol=1e-6)


def test_kernel_unknown_impl_raises():
    Y, eta, Lam = _sse_problem(10, 8, 4, seed=0)
    M, EYt, yty = _gram_operands(Y, eta, Lam)
    with pytest.raises(ValueError, match="impl"):
        gram_sse_ps(Lam, M, EYt, yty, jnp.ones((8,)), bs=0.3, impl="cuda")


# ---------------------------------------------------------------------------
# rejection-free Gamma draw: right moments at the psi-stage shapes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("a", [3.0, 21.5, 101.0])
def test_gamma_unit_static_moments(a):
    """The Exp-sum construction (+ half chi-square for half-integer
    shapes) must reproduce Gamma(a, 1) mean AND variance - both equal a
    - within 5 standard errors at the integer/half-integer shapes the
    psi stage uses (a = as_ + n/2)."""
    N = 40_000
    g = np.asarray(gamma_unit_static(jax.random.key(int(a)), a, (N,)))
    assert np.all(g > 0)
    se_mean = np.sqrt(a / N)
    assert abs(g.mean() - a) < 5 * se_mean, (g.mean(), a)
    # Var[Gamma(a,1)] = a; SE of the sample variance ~ sqrt(2/N)*a
    assert abs(g.var() - a) < 5 * np.sqrt(2.0 / N) * (a + 1), (g.var(), a)


def test_gamma_unit_static_fractional_falls_back():
    """Non-half-integer shapes can't use the Exp-sum construction; the
    draw must still be a valid Gamma(a, 1) via the rejection sampler."""
    a, N = 2.3, 40_000
    g = np.asarray(gamma_unit_static(jax.random.key(1), a, (N,)))
    assert np.all(g > 0)
    assert abs(g.mean() - a) < 5 * np.sqrt(a / N)


# ---------------------------------------------------------------------------
# checkpoints: sse_mode is metadata, not identity - resumes flip freely
# ---------------------------------------------------------------------------

def test_gram_checkpoint_roundtrip_and_mode_flip(tmp_path, data):
    """A gram fit records sse_mode in the checkpoint meta; resuming the
    finished run is a no-op returning the identical posterior; and a
    resume that FLIPS the mode is adopted, not refused - the carry
    layout is mode-independent and both strategies sample the same
    conditional (contrast compute_dtype, which refuses)."""
    import json

    Y, _ = data
    ck = str(tmp_path / "ck.npz")
    cfg = _cfg("gram", chunk=8, checkpoint_path=ck)
    res = fit(Y, cfg)
    with np.load(ck) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
    assert meta["config"]["backend"]["sse_mode"] == "gram"
    res2 = fit(Y, dataclasses.replace(cfg, resume=True))
    np.testing.assert_array_equal(res.Sigma, res2.Sigma)
    # the flip: a finished gram donor resumed under resid is adopted
    res3 = fit(Y, dataclasses.replace(_cfg("resid", chunk=8,
                                           checkpoint_path=ck),
                                      resume=True))
    np.testing.assert_array_equal(res.Sigma, res3.Sigma)


def test_midrun_resume_across_mode_flip(tmp_path, monkeypatch, data):
    """A gram chain killed mid-run and resumed under resid FINISHES the
    schedule: the adopted mode governs the remaining chunks and the
    result stays finite (the exchangeability contract makes this legal,
    the mode-independent carry layout makes it mechanical)."""
    import dcfm_tpu.runtime.pipeline as pipeline
    from tests.test_checkpoint import Killed, _SyncWriter

    Y, _ = data
    ck = str(tmp_path / "ck.npz")
    cfg = dataclasses.replace(_cfg("gram", chunk=8, checkpoint_path=ck),
                              checkpoint_every_chunks=1)
    monkeypatch.setattr(pipeline, "AsyncCheckpointWriter", _SyncWriter)
    real_save = pipeline.save_checkpoint
    calls = {"n": 0}

    def killing_save(*args, **kwargs):
        real_save(*args, **kwargs)
        calls["n"] += 1
        if calls["n"] == 2:
            raise Killed("simulated crash mid-chain")

    monkeypatch.setattr(pipeline, "save_checkpoint", killing_save)
    with pytest.raises(Killed):
        fit(Y, cfg)
    monkeypatch.setattr(pipeline, "save_checkpoint", real_save)

    res = fit(Y, dataclasses.replace(_cfg("resid", chunk=8,
                                          checkpoint_path=ck),
                                     checkpoint_every_chunks=1,
                                     resume=True))
    assert np.all(np.isfinite(res.Sigma))
