"""Trace-gate tests: the DCFM18xx jaxpr invariants on deliberately
broken entries, the shipped registry's clean run, and the partition
rule table's unmatched-leaf diagnostics.

Everything here traces abstractly (ShapeDtypeStruct inputs) - nothing
compiles or executes - so the whole module stays fast despite walking
real gibbs-sweep jaxprs.  The broken entries register under the
``fixture.`` prefix; ``discover()`` filters them out by builder path,
which is itself pinned below.
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from dcfm_tpu.analysis import tracecheck
from dcfm_tpu.analysis.engine import to_sarif
from dcfm_tpu.analysis.registry import (SkipEntry, TraceKeyRegistry,
                                        TraceSpec, discover, entries, get,
                                        register_trace_entry)
from dcfm_tpu.analysis.rules import TRACE_RULES
from dcfm_tpu.parallel.mesh import (CHAIN_AXIS, HOST_AXIS, SHARD_AXIS,
                                    make_chain_mesh, make_pod_mesh,
                                    match_partition_rules)
from dcfm_tpu.parallel.shard import shard_map

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual CPU devices")

_f32 = jnp.float32


def _sds(shape):
    return jax.ShapeDtypeStruct(shape, _f32)


# ---------------------------------------------------------------------------
# deliberately-broken entries (the trace twin of the bad_* lint fixtures)
# ---------------------------------------------------------------------------

@register_trace_entry("fixture.chains_psum", sweep_body=True)
def _chains_psum_spec():
    """A sweep body that pools across chains mid-sweep: the exact
    PR-12 violation DCFM1802 exists to catch."""
    mesh = make_chain_mesh(2, 4)

    def body(x):
        pooled = jax.lax.psum(x, CHAIN_AXIS)          # the violation
        return pooled + jax.lax.psum(x, SHARD_AXIS)   # this one is fine

    fn = shard_map(body, mesh=mesh,
                   in_specs=P(CHAIN_AXIS, SHARD_AXIS),
                   out_specs=P(None, None))
    return TraceSpec(fn=fn, args=(_sds((2, 2)),), mesh=mesh)


@register_trace_entry("fixture.shards_psum", sweep_body=True)
def _shards_psum_spec():
    """The sanctioned twin: the same reduction over the shard axis."""
    mesh = make_chain_mesh(2, 4)

    def body(x):
        return jax.lax.psum(x, SHARD_AXIS)

    fn = shard_map(body, mesh=mesh,
                   in_specs=P(CHAIN_AXIS, SHARD_AXIS),
                   out_specs=P(CHAIN_AXIS, None))
    return TraceSpec(fn=fn, args=(_sds((2, 2)),), mesh=mesh)


@register_trace_entry("fixture.hosts_psum", sweep_body=True)
def _hosts_psum_spec():
    """A sweep body that pools over the hosts axis alone: partial
    per-host state mixes mid-sweep, the DCFM1808 violation."""
    mesh = make_pod_mesh(2, 8)

    def body(x):
        leaked = jax.lax.psum(x, HOST_AXIS)            # the violation
        return leaked + jax.lax.psum(x, SHARD_AXIS)    # this one is fine

    fn = shard_map(body, mesh=mesh,
                   in_specs=P((HOST_AXIS, SHARD_AXIS)),
                   out_specs=P(None))
    return TraceSpec(fn=fn, args=(_sds((8,)),), mesh=mesh)


@register_trace_entry("fixture.pair_psum", sweep_body=True)
def _pair_psum_spec():
    """The sanctioned twin: the X-update/conquer shape, reducing over
    the FULL (hosts, shards) pair axis in one collective."""
    mesh = make_pod_mesh(2, 8)

    def body(x):
        full = jax.lax.psum(x, (HOST_AXIS, SHARD_AXIS))
        off = jax.lax.axis_index(HOST_AXIS)            # coordinates: exempt
        return full + off.astype(_f32)

    fn = shard_map(body, mesh=mesh,
                   in_specs=P((HOST_AXIS, SHARD_AXIS)),
                   out_specs=P(None))
    return TraceSpec(fn=fn, args=(_sds((8,)),), mesh=mesh)


@register_trace_entry("fixture.bf16_leak")
def _bf16_leak_spec():
    """A bfloat16 cast inside the f32-default graph (DCFM1803)."""
    def fn(x):
        return jnp.sum(x.astype(jnp.bfloat16)).astype(_f32)

    return TraceSpec(fn=fn, args=(_sds((8, 8)),))


@register_trace_entry("fixture.unpinned_dot")
def _unpinned_dot_spec():
    """bf16 mode with an unpinned accumulation (DCFM1804)."""
    def fn(a, b):
        return jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))

    return TraceSpec(fn=fn, args=(_sds((4, 4)), _sds((4, 4))),
                     compute_dtype="bf16")


@register_trace_entry("fixture.pinned_dot")
def _pinned_dot_spec():
    """The sanctioned `mm` pattern: low-precision multiply, f32 accum."""
    def fn(a, b):
        return jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                       preferred_element_type=_f32)

    return TraceSpec(fn=fn, args=(_sds((4, 4)), _sds((4, 4))),
                     compute_dtype="bf16")


@register_trace_entry("fixture.callback")
def _callback_spec():
    """A host callback in the hot path (DCFM1805)."""
    def fn(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2.0

    return TraceSpec(fn=fn, args=(_sds((4,)),))


@register_trace_entry("fixture.undonated_carry", donate_argnum=2)
def _undonated_carry_spec():
    """A chunk-shaped entry that forgot donate_argnums (DCFM1806)."""
    def chunk(y, sched, carry):
        return {"state": carry["state"] + jnp.sum(y) + sched[0]}

    return TraceSpec(fn=chunk,
                     args=(_sds((4,)), _sds((2,)),
                           {"state": _sds((4,))}))


@register_trace_entry("fixture.donated_carry", donate_argnum=2)
def _donated_carry_spec():
    """The fixed twin: same chunk, carry donated."""
    def chunk(y, sched, carry):
        return {"state": carry["state"] + jnp.sum(y) + sched[0]}

    return TraceSpec(fn=chunk,
                     args=(_sds((4,)), _sds((2,)),
                           {"state": _sds((4,))}),
                     donate_argnums=(2,))


@register_trace_entry("fixture.mutable_key")
def _mutable_key_spec():
    """A static cache key carrying a dict and an identity-hashed
    object: both defeat jit's trace cache (DCFM1807)."""
    return TraceSpec(fn=lambda x: x * 2.0, args=(_sds((2,)),),
                     static_key=({"rho": 0.8}, object()))


@register_trace_entry("fixture.broken_builder")
def _broken_builder_spec():
    raise RuntimeError("representative mesh construction exploded")


@register_trace_entry("fixture.concrete_dep")
def _concrete_dep_spec():
    """A data-dependent Python branch: untraceable abstractly."""
    def fn(x):
        if x[0] > 0:
            return x
        return -x

    return TraceSpec(fn=fn, args=(_sds((2,)),))


@register_trace_entry("fixture.skipped")
def _skipped_spec():
    raise SkipEntry("needs 1024 devices")


def _fired(name):
    return {f.rule for f in tracecheck.check_entry(get(name))}


# ---------------------------------------------------------------------------
# per-rule: exact finding sets on the broken entries
# ---------------------------------------------------------------------------

def test_chains_spanning_psum_fires_1802():
    findings = tracecheck.check_entry(get("fixture.chains_psum"))
    assert {f.rule for f in findings} == {"DCFM1802"}
    assert len(findings) == 1
    assert "'chains'" in findings[0].message
    assert findings[0].message.startswith("[fixture.chains_psum]")


def test_shard_axis_psum_is_sanctioned():
    assert tracecheck.check_entry(get("fixture.shards_psum")) == []


def test_hosts_only_psum_fires_1808():
    findings = tracecheck.check_entry(get("fixture.hosts_psum"))
    assert {f.rule for f in findings} == {"DCFM1808"}
    assert len(findings) == 1
    assert "'hosts'" in findings[0].message
    assert "X update" in findings[0].message


def test_full_pair_psum_and_host_axis_index_are_sanctioned():
    assert tracecheck.check_entry(get("fixture.pair_psum")) == []


def test_bf16_leak_in_f32_graph_fires_1803():
    findings = tracecheck.check_entry(get("fixture.bf16_leak"))
    assert {f.rule for f in findings} == {"DCFM1803"}
    assert "bfloat16" in findings[0].message
    assert "f32-default graph" in findings[0].message


def test_unpinned_bf16_dot_fires_1804():
    findings = tracecheck.check_entry(get("fixture.unpinned_dot"))
    assert {f.rule for f in findings} == {"DCFM1804"}
    assert "preferred_element_type" in findings[0].message


def test_pinned_bf16_dot_is_clean():
    assert tracecheck.check_entry(get("fixture.pinned_dot")) == []


def test_host_callback_fires_1805():
    assert _fired("fixture.callback") == {"DCFM1805"}


def test_undonated_carry_fires_1806():
    findings = tracecheck.check_entry(get("fixture.undonated_carry"))
    assert {f.rule for f in findings} == {"DCFM1806"}
    assert "argument 2" in findings[0].message
    assert "donate_argnums=(2,)" in findings[0].message


def test_donated_carry_is_clean():
    assert tracecheck.check_entry(get("fixture.donated_carry")) == []


def test_mutable_static_key_fires_1807_per_component():
    findings = tracecheck.check_entry(get("fixture.mutable_key"))
    assert [f.rule for f in findings] == ["DCFM1807", "DCFM1807"]
    msgs = "\n".join(f.message for f in findings)
    assert "component #0" in msgs and "dict" in msgs
    assert "component #1" in msgs and "identity" in msgs


def test_builder_failure_fires_1800():
    findings = tracecheck.check_entry(get("fixture.broken_builder"))
    assert {f.rule for f in findings} == {"DCFM1800"}
    assert "entry builder failed" in findings[0].message


def test_concrete_value_dependence_fires_1800():
    findings = tracecheck.check_entry(get("fixture.concrete_dep"))
    assert {f.rule for f in findings} == {"DCFM1800"}
    assert "abstract trace failed" in findings[0].message


def test_skip_entry_yields_no_findings():
    assert tracecheck.check_entry(get("fixture.skipped")) == []


def test_findings_anchor_at_the_registration_site():
    entry = get("fixture.chains_psum")
    f = tracecheck.check_entry(entry)[0]
    assert f.path == entry.path
    assert f.path.endswith("test_tracecheck.py")
    assert f.line == entry.line > 0


# ---------------------------------------------------------------------------
# retrace sentinel internals
# ---------------------------------------------------------------------------

def test_key_registry_sanctions_the_frozen_config_vocabulary():
    from dcfm_tpu import ModelConfig
    reg = TraceKeyRegistry()
    cfg = ModelConfig(num_shards=2, factors_per_shard=3, rho=0.8)
    assert reg.record("e", (cfg, 4, "quant8", (("shards", 2),))) == []


def test_key_registry_flags_non_frozen_dataclass():
    # eq=True (the default) deletes __hash__ entirely: the unhashable
    # branch; eq=False keeps object identity hashing: the silent
    # per-call-retrace branch.  Both are DCFM1807 material.
    @dataclasses.dataclass
    class UnhashableCfg:
        n: int = 1

    @dataclasses.dataclass(eq=False)
    class IdentityCfg:
        n: int = 1

    reg = TraceKeyRegistry()
    problems = reg.record("e", (UnhashableCfg(), IdentityCfg()))
    assert [i for i, _ in problems] == [0, 1]
    assert "unhashable" in problems[0][1]
    assert "non-frozen dataclass" in problems[1][1]


# ---------------------------------------------------------------------------
# the whole-registry gate: discovery, isolation, clean run, cache
# ---------------------------------------------------------------------------

def test_discover_filters_fixture_entries():
    names = {e.name for e in discover()}
    assert names, "library registered no trace entries"
    assert not any(n.startswith("fixture.") for n in names)
    # ...even though the raw registry does hold them (imported above)
    assert any(n.startswith("fixture.") for n in entries())


def test_shipped_registry_verifies_clean():
    """The acceptance gate: every registered library entry passes every
    DCFM18xx check (what `dcfm-tpu lint --trace` runs in CI)."""
    findings = tracecheck.check_entries(discover())
    assert findings == [], "\n".join(str(f) for f in findings)


def test_trace_cache_round_trip(tmp_path):
    cache = str(tmp_path / "tc.json")
    first = tracecheck.check_project(cache_path=cache, root=REPO)
    assert first == []
    with open(cache, encoding="utf-8") as f:
        data = json.load(f)
    assert set(data["entries"]) == {e.name for e in discover()}
    # warm run serves every entry from the module-hash cache
    assert tracecheck.check_project(cache_path=cache, root=REPO) == []


def test_trace_changed_without_git_raises(tmp_path):
    with pytest.raises(RuntimeError, match="--changed"):
        tracecheck.check_project(changed_only=True, root=str(tmp_path))


def test_trace_findings_serialize_to_sarif():
    findings = tracecheck.check_entry(get("fixture.chains_psum"))
    log = to_sarif(findings, REPO)
    assert log["version"] == "2.1.0"
    driver = log["runs"][0]["tool"]["driver"]
    assert set(TRACE_RULES) <= {r["id"] for r in driver["rules"]}
    res = log["runs"][0]["results"][0]
    assert res["ruleId"] == "DCFM1802"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("test_tracecheck.py")
    assert loc["region"]["startLine"] >= 1


def test_cli_trace_gate_is_clean():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.analysis", "--trace"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_trace_gate_shares_the_baseline_without_clobbering_ast_debt(
        tmp_path):
    """One LINT_BASELINE.json, partitioned by rule family: the trace
    gate neither reports the AST entries as stale nor wipes them on
    --write-baseline."""
    base = tmp_path / "b.json"
    base.write_text(json.dumps({"version": 1, "entries": [
        {"fingerprint": "0" * 40, "rule": "DCFM101",
         "path": "scripts/x.py", "text": "k reused"}]}))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")

    gated = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.analysis", "--trace",
         "--baseline", str(base)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert gated.returncode == 0, gated.stdout + gated.stderr
    assert "stale" not in gated.stdout

    wrote = subprocess.run(
        [sys.executable, "-m", "dcfm_tpu.analysis", "--trace",
         "--baseline", str(base), "--write-baseline"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    entries_after = json.loads(base.read_text())["entries"]
    assert [e["rule"] for e in entries_after] == ["DCFM101"]


# ---------------------------------------------------------------------------
# partition-rule conformance: the table's unmatched-leaf diagnostics
# ---------------------------------------------------------------------------

def test_unmatched_leaf_error_names_nearest_miss_and_table():
    """The one-edit typo case: the exception alone must be enough to
    diagnose which rule was meant."""
    rules = [(r"\.state\.Lambda$", P(SHARD_AXIS)),
             (r"\.state\.X$", P())]
    tree = {"state": {"Lamda": _sds((4, 4))}}       # typo'd leaf
    with pytest.raises(ValueError) as exc:
        match_partition_rules(rules, tree)
    msg = str(exc.value)
    assert "no partition rule matches carry leaf" in msg
    assert "nearest miss: rule #" in msg
    assert "similarity" in msg
    assert "rule table (first match wins):" in msg
    assert "#0:" in msg and "#1:" in msg
    assert repr(r"\.state\.X$") in msg              # full table printed


def test_callable_rules_and_scalar_passthrough():
    rules = [(r".", lambda leaf: P() if len(leaf.shape) == 0
              else P(SHARD_AXIS))]
    specs = match_partition_rules(
        rules, {"a": _sds((4,)), "b": _sds(())}, scalar_spec=None)
    assert specs == {"a": P(SHARD_AXIS), "b": P()}


def test_scalars_skip_the_table_by_default():
    # an empty table would raise for any consulted leaf; scalars never
    # consult it
    assert match_partition_rules([], {"n": _sds(())}) == {"n": P()}
